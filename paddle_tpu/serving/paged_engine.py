"""Paged continuous-batching engine — resident HBM as the unit of win.

``ServingEngine``'s decode slab reserves a full ``[S_max]`` row per
request, so short requests waste most of their residency and the
concurrency ceiling is ``HBM / (S_max * token_bytes)`` regardless of
actual lengths. This engine keeps K/V in a PAGE ARENA
(:class:`~.paged_pool.PagedKVPool`) and each request claims only
``ceil(total_tokens / page_size)`` pages — at equal KV HBM, a
mixed-length workload admits strictly more concurrent requests (the
tier-1 test pins it against the slab engine, same budget, same
workload).

Compiled-program inventory (all fixed-shape, admission/retirement never
recompiles — the slab engine's core discipline carries over):

- **prefill** (per power-of-two prompt bucket): unchanged — the shared
  per-bucket programs from the base engine run the padded prompt
  through a transient block from the bucketed block pool.
- **adopt-pages** (per bucket): scatters the prefilled ``[1, bucket]``
  block into the arena as ``bucket / page_size`` whole pages at
  table-supplied ids (tail ids past the request's claim point at the
  garbage page 0 — no shape variance, no recompiles).
- **decode step** (exactly one): ``[B]`` tokens + the ``[B, P_max]``
  page table -> next tokens; attention gathers K/V through the table
  (``models.llama`` paged path; a tuned Pallas paged-attention kernel
  replaces the HBM gather when the tune cache opts one in).
- **gather-pages** (per bucket, prefix-cache mode): materializes a
  request's cached-prefix pages as a prefill-layout block so the tail
  program can attend over them.
- **chunk-prefill** (per (bucket, tail-bucket) pair, prefix-cache
  mode): runs ONLY the uncached tail of a prompt at a traced position
  offset — the warm path's near-zero prefill compute.

Prefill/decode disaggregation: prefill and decode are separate
compiled units, and ``max_prefills_per_step`` (default 1) bounds how
many prompt prefills one engine step may run before the decode step
fires — a burst of long prompts delays in-flight decodes by at most one
bucket's prefill per step instead of stalling them behind the whole
backlog. Prefilled requests enter the decode batch purely by having
their pages written and their table row set.

PREFIX CACHING (``prefix_cache=True`` / a ``PrefixCache``): prefill
pages are published under ``(weights_version, cache_dtype,
token-prefix hash chain)`` keys at page granularity with refcounts; a
new request adopts every matching full page BY REFERENCE into its page
table, prefill runs only on the uncached tail, a recompute boundary
inside a shared page copy-on-write clones it through the gather ->
chunk -> adopt pipeline, cold refcount-zero prefixes are LRU-evicted
under arena pressure, and a weight reload flushes the store. Prefix
mode also switches decode pages to DEMAND GROWTH (``demand_paging=``
to control it independently): admission claims only the prompt's
pages, each decode step claims the next page as a row crosses a page
boundary, and a growth failure sheds THAT request with reason
``pages_exhausted`` — never a crash, never another row's pages.

Token streams are exact-equal to ``net.generate`` and the slab engine
— including warm prefix hits: adopted KV is prefill-provenance content
for the identical token prefix under identical weights, and the
chunked tail program is pinned bitwise-equal to the full prefill.
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..models.generation import _select_next, decode_step
from ..observability.tracing import get_tracer
from .engine import (
    ServingEngine,
    _Seq,
    _flatten,
    _unflatten,
    build_chunk_prefill_body,
)
from .paged_pool import PagedKVPool, PagesExhausted
from .scheduler import CANCELLED, REASON_PAGES_EXHAUSTED, RUNNING


class PagedServingEngine(ServingEngine):
    """Continuous batching over a paged KV pool.

    Same request surface as :class:`ServingEngine` (submit / step /
    run_until_idle / generate / close, streaming callbacks, scheduler,
    metrics). Geometry: ``page_size`` must be a power of two that
    divides ``min_bucket`` AND ``max_seq_len`` (adoption scatters whole
    pages; the top prompt bucket is capped at ``max_seq_len``).
    ``num_pages`` (usable pages, garbage page excluded) defaults to
    full-coverage ``max_batch_size * ceil(max_seq_len / page_size)`` —
    pass a smaller arena to trade concurrency headroom for HBM, the
    whole point of paging.

    ``prefix_cache=True`` (or a :class:`~.prefix_cache.PrefixCache`
    over the same pool) enables copy-on-write prefix page sharing;
    ``demand_paging`` defaults to the prefix-cache setting and grows
    decode pages per step instead of claiming them up front."""

    def __init__(self, net, *, max_batch_size=8, max_seq_len=256,
                 page_size=16, num_pages=None, cache_dtype=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=0, min_bucket=16, max_queue_size=64,
                 max_tokens_in_flight=None, max_prefills_per_step=1,
                 scheduler=None, metrics=None, pool=None, page_pool=None,
                 clock=time.monotonic, recompile_guard_max=None,
                 weights_version=None, prefill_transport=None,
                 reload_template=None, prefix_cache=None,
                 demand_paging=None, speculative=None,
                 kv_tiering=None, sessions=None):
        ps = int(page_size)
        if ps < 1 or (ps & (ps - 1)):
            raise ValueError(
                f"page_size must be a power of two, got {page_size}"
            )
        if ps > int(min_bucket) or int(min_bucket) % ps:
            raise ValueError(
                f"page_size {ps} must divide every prefill bucket: "
                f"min_bucket {min_bucket} must be a multiple of it"
            )
        if int(max_seq_len) % ps:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"page_size {ps} (the top prompt bucket is capped at "
                f"max_seq_len and adoption scatters whole pages)"
            )
        self.page_size = ps
        self._num_pages_arg = num_pages
        self._page_pool_arg = page_pool
        self._prefix_cache_arg = prefix_cache
        # hierarchical KV tiering (kv_tiering.TieredPageStore): True
        # builds a default host-RAM tier, a dict passes ctor kwargs
        # through, a built store attaches as-is. Requires a prefix
        # cache — the tier spills/restores ITS pages.
        self._kv_tiering_arg = kv_tiering
        if kv_tiering not in (None, False) \
                and prefix_cache in (None, False):
            raise ValueError(
                "kv_tiering requires prefix_cache: the tier spills "
                "and restores prefix-cache pages"
            )
        self._demand_paging = (
            bool(demand_paging) if demand_paging is not None
            else prefix_cache not in (None, False)
        )
        self.max_prefills_per_step = (
            None if max_prefills_per_step is None
            else int(max_prefills_per_step)
        )
        # cross-process disaggregation: when a transport (a
        # fleet.kv_transfer.RemotePrefillClient) is attached, admission
        # ships the prompt to the prefill pool and adopts the returned
        # KV pages; any transfer failure falls back to LOCAL prefill on
        # this engine — disaggregation is an optimization, never a
        # correctness dependency. A prefix-cache hit skips the
        # transport entirely (the tail chunk is cheaper than the wire).
        self.prefill_transport = prefill_transport
        self.remote_prefills = 0
        self.local_prefills = 0
        self.chunk_prefills = 0
        self.remote_prefill_fallbacks = 0
        super().__init__(
            net, max_batch_size=max_batch_size, max_seq_len=max_seq_len,
            cache_dtype=cache_dtype, do_sample=do_sample,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            min_bucket=min_bucket, max_queue_size=max_queue_size,
            max_tokens_in_flight=max_tokens_in_flight,
            scheduler=scheduler, metrics=metrics, pool=pool, clock=clock,
            recompile_guard_max=recompile_guard_max,
            weights_version=weights_version,
            reload_template=reload_template,
            speculative=speculative, sessions=sessions,
        )
        if self.prefix_cache is not None and recompile_guard_max is None:
            # prefix mode legitimately compiles one gather program per
            # bucket and one chunk program per (bucket, tail-bucket)
            # pair — widen the storm bar to the real steady-state
            # inventory instead of firing on warm-path compiles. A
            # spill tier adds ONE more: the page-size restore adopt.
            nb = len(self._warmup_buckets())
            self.trace_guard.max_compiles = max(
                self.trace_guard.max_compiles,
                nb * (nb + 3) // 2 + 2
                + (1 if self.kv_tier is not None else 0),
            )

    # ------------------------------------------------------- KV backend
    def _init_kv_backend(self):
        num_pages = self._num_pages_arg
        if num_pages is None:
            num_pages = (self.max_batch_size
                         * (-(-self.max_seq_len // self.page_size)))
        pp = self._page_pool_arg or PagedKVPool(
            self.config, page_size=self.page_size, num_pages=num_pages,
            dtype=self.cache_dtype, max_seq_len=self.max_seq_len,
        )
        if pp.page_size != self.page_size:
            raise ValueError(
                f"page_pool page_size {pp.page_size} != engine "
                f"page_size {self.page_size}"
            )
        if jnp.dtype(pp.dtype) != jnp.dtype(self.cache_dtype):
            raise ValueError(
                f"page_pool dtype {pp.dtype} != prefill block dtype "
                f"{self.cache_dtype} — adoption would silently cast"
            )
        if pp.table_width() * pp.page_size < self.max_seq_len:
            raise ValueError(
                f"page_pool table width {pp.table_width()} covers only "
                f"{pp.table_width() * pp.page_size} tokens < engine "
                f"max_seq_len {self.max_seq_len}"
            )
        self.page_pool = pp
        pc = self._prefix_cache_arg
        if pc is True:
            from .prefix_cache import PrefixCache

            pc = PrefixCache(pp)
        elif pc in (None, False):
            pc = None
        elif pc.pool is not pp:
            raise ValueError(
                "prefix_cache wraps a different PagedKVPool than this "
                "engine's — pass the same pool to both"
            )
        self.prefix_cache = pc
        tier = getattr(self, "_kv_tiering_arg", None)
        if tier is True:
            from .kv_tiering import TieredPageStore

            tier = TieredPageStore()
        elif isinstance(tier, dict):
            from .kv_tiering import TieredPageStore

            tier = TieredPageStore(**tier)
        elif tier in (None, False):
            tier = None
        self.kv_tier = tier
        if tier is not None:
            pc.attach_tier(
                tier,
                read_page=self._tier_read_page,
                restore_page=self._tier_restore_page,
                current_version=lambda: self.weights_version,
            )
        self.table_width = pp.table_width()
        self._flat = _flatten(pp.alloc_arena_arrays())
        self._tables = np.zeros(
            (self.max_batch_size, self.table_width), np.int32
        )
        self._row_pages = [None] * self.max_batch_size
        self._row_meta = [None] * self.max_batch_size
        self._free_rows = list(range(self.max_batch_size))[::-1]
        self._gather_fns = {}   # bucket -> jitted fn
        self._chunk_fns = {}    # (bucket, tail_bucket) -> jitted fn
        # speculative-verify page accounting (the zero-leak pin reads
        # these: every transient verify page claimed must either stay
        # owned by the accepting request or come back on rollback)
        self.spec_pages_claimed = 0
        self.spec_pages_rolled_back = 0

    def _release_slot(self, slot):
        if self.speculative is not None:
            self.speculative.reset_slot(slot)
        pages = self._row_pages[slot]
        meta = self._row_meta[slot]
        if (pages and meta is not None and self.prefix_cache is not None
                and not self._closed):
            # publish-on-finish: the partial prompt-tail page becomes
            # shareable the moment its owner stops writing it (a later
            # same-prefix request COW-adopts it instead of re-running
            # the tail) — prefill-valid slots only, decode KV never
            prompt, prompt_len = meta
            r = prompt_len % self.page_size
            k = prompt_len // self.page_size
            if r and k < len(pages):
                self.prefix_cache.publish_partial(
                    prompt, prompt_len, pages[k], self.weights_version
                )
        if pages:
            self.page_pool.release(pages)
        if self.prefix_cache is not None:
            self.prefix_cache.update_gauges()
        self._row_pages[slot] = None
        self._row_meta[slot] = None
        self._tables[slot, :] = 0  # free row reads/writes garbage page
        self._free_rows.append(slot)

    def _finish(self, slot, status, reason=None):
        """Decode-publish, then the base terminal transition. While
        the row's sequence and pages are still live, every page the
        finished request WROTE — prompt AND generated answer — is
        published into the prefix chain: the decode step and the
        prefill program share one masked-SDPA op order (pinned
        bitwise-equal in tier-1, bf16 and int8), so decode-written KV
        for position ``p`` is byte-for-byte what re-prefilling
        ``tokens[0..p]`` would write. Valid span: the LAST emitted
        token's KV is never written (nothing consumed it), so
        ``prompt_len + emitted - 1`` positions publish — turn N+1 of
        a chat warm-admits turn N's full context including the
        answer."""
        seq = self._seqs[slot]
        if (seq is not None and self.prefix_cache is not None
                and not self._closed):
            pages = self._row_pages[slot]
            meta = self._row_meta[slot]
            if pages and meta is not None:
                h = seq.handle
                prompt, prompt_len = meta
                toks = prompt + tuple(int(t) for t in h.tokens)
                valid = prompt_len + max(0, len(h.tokens) - 1)
                if valid > prompt_len:
                    self.prefix_cache.publish(
                        toks, valid, pages, self.weights_version
                    )
                    ps = self.page_size
                    k, r = valid // ps, valid % ps
                    if r and k < len(pages):
                        self.prefix_cache.publish_partial(
                            toks, valid, pages[k], self.weights_version
                        )
        super()._finish(slot, status, reason=reason)

    @property
    def free_rows(self):
        return len(self._free_rows)

    def _has_capacity(self):
        return bool(self._free_rows)

    def _too_long(self, req):
        # a request needing more pages than the whole arena would sit
        # at the head of the strict-FIFO queue forever, blocking every
        # later request — reject it at submit instead
        return (super()._too_long(req)
                or self.page_pool.pages_for(req.total_tokens)
                > self.page_pool.num_pages)

    def _pages_at_admission(self, prompt_len, total_tokens):
        """Pages a request's table needs when admitted: the whole span
        up front classically; only the prompt's pages under demand
        growth (decode pages are claimed per step as rows cross page
        boundaries)."""
        return self.page_pool.pages_for(
            prompt_len if self._demand_paging else total_tokens
        )

    def _admission_budget(self):
        """Head must fit BOTH the in-flight token cap and the free
        pages. ``total <= free_pages * page_size`` is exactly
        ``ceil(total / page_size) <= free_pages``, so the token-budget
        gate doubles as the page gate — strict FIFO is preserved (a big
        head waits, nothing overtakes it). In prefix/demand mode the
        page side moves to :meth:`_admission_fits` (a warm request's
        real need depends on cache coverage, which a scalar budget
        cannot express)."""
        base = ServingEngine._admission_budget(self)
        if self._demand_paging or self.prefix_cache is not None:
            return base
        page_budget = self.page_pool.free_pages * self.page_size
        return page_budget if base is None else min(base, page_budget)

    def _admission_fits(self):
        if self.prefix_cache is None and not self._demand_paging:
            return None

        def fits(req):
            n_init = self._pages_at_admission(req.prompt_len,
                                              req.total_tokens)
            n_ref = 0
            ref_pages = ()
            match, plan = self._prefix_probe(req)
            if plan is not None:
                n_ref = plan[0] // self.page_size
                ref_pages = match.pages[:n_ref]
            need = n_init - n_ref
            if need <= self.page_pool.free_pages:
                return True  # freelist covers it — skip the cache walk
            if self.prefix_cache is None:
                return False
            # the pages this request would ADOPT are excluded: eviction
            # can never reclaim what admission is about to reference —
            # counting them would pass a head whose claim then fails
            return need <= (self.page_pool.free_pages
                            + self.prefix_cache.evictable_pages(
                                exclude=ref_pages))

        return fits

    def _prefix_probe(self, req):
        """One chain walk + chunk plan per request per admission
        attempt, shared between the fits predicate and ``_admit_one``
        (same driver thread, nothing mutates the cache between the pop
        check and the admission that immediately follows it). The
        result is stashed on the request and consumed by admission;
        a head that waits re-probes on its next pop attempt."""
        if self.prefix_cache is None:
            return None, None
        m = self.prefix_cache.match(req.input_ids, req.prompt_len,
                                    self.weights_version)
        plan = None
        if m.covered > 0:
            bucket = self.pool.bucket_for(req.prompt_len)
            plan = self._chunk_plan(req.prompt_len, bucket, m.covered)
        out = (m if plan is not None else None, plan)
        req.__dict__["_prefix_probe_result"] = out
        return out

    def _max_admissions_per_step(self):
        return self.max_prefills_per_step

    # ------------------------------------------------- compiled programs
    def _decode_body(self, params, buffers, tok, flat, tbl, pos,
                     temperature, key):
        self.net.load_functional_state(params, buffers)
        self.net.eval()
        logits, caches = decode_step(
            self.net, tok[:, None], _unflatten(flat), pos,
            page_table=tbl,
        )
        if self.do_sample:
            # per-row position-addressed keys (see the base engine)
            key = jax.vmap(jax.random.fold_in)(key, pos + 1)
        nxt = _select_next(logits, self.do_sample, temperature,
                           self.top_k, self.top_p, key)
        return nxt, _flatten(caches)

    def _decode_extra(self):
        return (jnp.asarray(self._tables),)

    def _adopt_fn(self, bucket):
        """Scatter a prefilled [1, bucket] block into the arena as
        ``bucket / page_size`` whole pages at traced page ids — one
        program per bucket, ids beyond the request's claim point at the
        garbage page 0 (duplicate scatter indices there are fine: the
        page is garbage by contract)."""
        fn = self._adopt_fns.get(bucket)
        if fn is not None:
            return fn
        ps = self.page_size
        n_pages_b = bucket // ps

        def body(flat_arena, flat_block, page_ids):
            from ..quantization.kv import adopt_into_pages

            return [
                adopt_into_pages(a, b, page_ids, n_pages_b, ps)
                for a, b in zip(flat_arena, flat_block)
            ]

        fn = jax.jit(
            body, donate_argnums=(0,) if self._donate else ()
        )
        self._adopt_fns[bucket] = fn
        self.trace_guard.record_compile(
            "serving::adopt_pages", bucket,
            origin="serving/paged_engine.py",
        )
        return fn

    def _gather_fn(self, bucket):
        """Materialize ``bucket / page_size`` arena pages at traced ids
        as one prefill-layout block — the warm path's cached-prefix
        context (ids past the cached span -> garbage page 0, whose
        content sits behind the position mask like any stale slot). The
        arena is NOT donated: shared pages must survive the gather."""
        fn = self._gather_fns.get(bucket)
        if fn is not None:
            return fn
        ps = self.page_size
        n_pages_b = bucket // ps

        def body(flat_arena, src_ids):
            from ..quantization.kv import gather_block_from_pages

            return [
                gather_block_from_pages(a, src_ids, n_pages_b, ps)
                for a in flat_arena
            ]

        fn = jax.jit(body)
        self._gather_fns[bucket] = fn
        self.trace_guard.record_compile(
            "serving::gather_pages", bucket,
            origin="serving/paged_engine.py",
        )
        return fn

    def _chunk_fn(self, bucket, tail_bucket):
        """The chunked-prefill program: tail tokens [1, tail_bucket] at
        a traced position offset over a gathered [1, bucket] block —
        one program per (bucket, tail-bucket) pair, O(log^2) total."""
        fn = self._chunk_fns.get((bucket, tail_bucket))
        if fn is not None:
            return fn
        body = build_chunk_prefill_body(self.net, self.do_sample,
                                        self.top_k, self.top_p)
        fn = jax.jit(
            body, donate_argnums=(5,) if self._donate else ()
        )
        self._chunk_fns[(bucket, tail_bucket)] = fn
        self.trace_guard.record_compile(
            "serving::chunk_prefill", (bucket, tail_bucket),
            origin="serving/paged_engine.py",
        )
        return fn

    def _adopt_example_args(self, flat_block, bucket):
        return (
            self._flat, flat_block,
            jnp.zeros((bucket // self.page_size,), jnp.int32),
        )

    def _program_signature(self, name):
        sig = super()._program_signature(name)
        sig["page_size"] = self.page_size
        sig["num_pages"] = self.page_pool.num_pages
        sig["table_width"] = self.table_width
        return sig

    # --------------------------------------------------- prefix caching
    def _tail_buckets(self, bucket):
        """The tail-chunk shape ladder for one prompt bucket: the
        power-of-two prefill ladder capped at the bucket itself."""
        out, L = [], int(getattr(self.pool, "min_bucket", 16))
        while L < bucket:
            out.append(L)
            L *= 2
        out.append(bucket)
        return out

    def _chunk_plan(self, prompt_len, bucket, covered):
        """Pick the warm path's (recompute start ``c``, tail bucket):
        maximize the cached span actually reused, under the hard shape
        constraint ``c + tail_bucket <= bucket`` (the chunk writes
        [c, c + tail_bucket) into the block — clamped dynamic slices
        would silently corrupt positions otherwise) and ``c <=
        prompt_len - 1`` (the last prompt token is always re-run: its
        logits produce the first output token). None when no plan
        reuses anything (degenerate -> cold path)."""
        best = None
        for tb in self._tail_buckets(bucket):
            c = min(int(covered), prompt_len - 1, bucket - tb)
            if c <= 0 or prompt_len - c > tb:
                continue
            if best is None or c > best[0]:
                best = (c, tb)
        return best

    def _claim_pages(self, n):
        """Fresh pages, evicting cold cached prefixes under pressure.
        Raises :class:`PagesExhausted` only when the freelist AND the
        reclaimable side of the cache together cannot cover ``n``."""
        try:
            return self.page_pool.claim(n)
        except PagesExhausted:
            if self.prefix_cache is None:
                raise
            need = n - self.page_pool.free_pages
            self.prefix_cache.evict(need)
            return self.page_pool.claim(n)

    # ------------------------------------------------------- KV tiering
    def _tier_read_page(self, page_id):
        """One arena page's bytes on the host, flattened one array per
        raw buffer (a QuantizedKV leaf contributes q then scale) — the
        spill side of the tier attachment. Read-only: shared pages are
        never touched, only copied out."""
        from ..quantization.kv import is_quantized

        out = []
        for leaf in self._flat:
            if is_quantized(leaf):
                out.append(np.asarray(leaf.q[page_id]))
                out.append(np.asarray(leaf.scale[page_id]))
            else:
                out.append(np.asarray(leaf[page_id]))
        return out

    def _page_block(self, arrays=None):
        """A [1, page_size]-wide flat block matching ``self._flat``'s
        leaf structure — from spilled host ``arrays`` (restore), or
        zeros (warmup example args). One shape for both, so the
        restore program warms with the exact block it later runs."""
        from ..quantization.kv import QuantizedKV, is_quantized

        ps = self.page_size
        block, i = [], 0
        for leaf in self._flat:
            if is_quantized(leaf):
                if arrays is None:
                    kvh, d = leaf.q.shape[2], leaf.q.shape[3]
                    q = jnp.zeros((1, ps, kvh, d), leaf.q.dtype)
                    s = jnp.zeros((1, ps, kvh), leaf.scale.dtype)
                else:
                    q = jnp.asarray(arrays[i])[None]
                    s = jnp.asarray(arrays[i + 1])[None]
                block.append(QuantizedKV(q, s))
                i += 2
            else:
                if arrays is None:
                    a = jnp.zeros((1, ps) + tuple(leaf.shape[2:]),
                                  leaf.dtype)
                else:
                    a = jnp.asarray(arrays[i])[None]
                block.append(a)
                i += 1
        return block

    def _tier_restore_page(self, arrays):
        """The restore side: claim one fresh arena page, adopt the
        spilled bytes into it through the page-size adopt program
        (same scatter the prefill path uses — restored bytes land
        bit-identical), return its id. None when the arena has no
        page to spare RIGHT NOW — the record stays spilled and the
        request cold-prefills; claiming directly from the pool (not
        ``_claim_pages``) keeps a restore from recursing into
        eviction, which could spill the very chain being walked."""
        try:
            page = self.page_pool.claim(1)
        except PagesExhausted:
            return None
        ps = self.page_size
        with profiler.RecordEvent(f"serving::restore_adopt_b{ps}"):
            self._flat = self._run(
                ("adopt", ps), self._adopt_fn(ps),
                self._flat, self._page_block(arrays),
                jnp.asarray(page, jnp.int32),
            )
        return page[0]

    # ------------------------------------------- speculative backend seams
    def _verify_widths(self, buckets):
        """Paged verify blocks are bucketed gathers — one verify
        program per prompt bucket, not one full-width program."""
        return list(buckets)

    def _warm_spec_gather(self, cache, stats, buckets):
        """The speculative round's per-bucket gather — the SAME
        programs (and ``("gather", b)`` warm keys) the prefix-cache
        warm path compiles, so with a prefix cache attached this is an
        idempotent no-op pass."""
        ps = self.page_size
        for b in buckets:
            self._warm_one(
                cache, f"gather_b{b}", ("gather", b),
                self._gather_fn(b),
                (self._flat, jnp.zeros((b // ps,), jnp.int32)),
                lambda comp, b=b: self._gather_fns
                .__setitem__(b, comp), stats,
            )

    def _spec_reserve(self, slot, hi):
        """Demand-claim pages so row ``slot`` holds KV capacity through
        cache position ``hi`` (the verify writes [pos, hi]); appended
        to the row's OWNED pages and table like any demand growth, so
        occupancy gauges count them while held. Under page pressure the
        round clamps to what the pool can cover — worst case the
        request's current position, a one-token vanilla-equivalent
        verify — instead of shedding anybody."""
        hi = min(hi, self.max_seq_len - 1)
        pages = self._row_pages[slot]
        ps = self.page_size
        while hi // ps >= len(pages):
            try:
                new = self._claim_pages(1)
            except PagesExhausted:
                break
            self._tables[slot, len(pages)] = new[0]
            pages.append(new[0])
            self.spec_pages_claimed += 1
        return min(hi, len(pages) * ps - 1)

    def _spec_gather(self, slot, hi):
        """Row ``slot``'s owned pages as one prefill-layout block wide
        enough to cover position ``hi`` — the same bucketed gather
        program the prefix-cache warm path runs (pad ids -> garbage
        page 0, masked)."""
        ps = self.page_size
        bucket = self.pool.bucket_for(hi + 1)
        pages = self._row_pages[slot]
        src = np.zeros((bucket // ps,), np.int32)
        n = min(len(pages), bucket // ps)
        src[:n] = pages[:n]
        with profiler.RecordEvent(f"serving::spec_gather_b{bucket}"):
            flat_block = self._run(
                ("gather", bucket), self._gather_fn(bucket),
                self._flat, jnp.asarray(src),
            )
        return flat_block, bucket

    def _spec_adopt(self, slot, new_block, width, pos):
        """Scatter the verify-updated block back — ONLY the pages the
        verify may have written (index >= pos // page_size; all owned
        exclusively: pos >= prompt_len, and shared prefix pages end at
        the prompt's last full-page boundary). Everything below
        scatters to garbage page 0, so a shared page is never written
        even with identical content."""
        ps = self.page_size
        pages = self._row_pages[slot]
        page_ids = np.zeros((width // ps,), np.int32)
        lo = pos // ps
        n = min(len(pages), width // ps)
        page_ids[lo:n] = pages[lo:n]
        self._flat = self._run(
            ("adopt", width), self._adopt_fn(width),
            self._flat, new_block, jnp.asarray(page_ids),
        )

    def _spec_rollback(self, slot, new_pos):
        """Release the rejected tail's demand-claimed pages (anything
        past the page holding ``new_pos``) back to the pool and zero
        their table entries — the zero-leak pin. Classic (non-demand)
        mode keeps the row's full up-front span untouched."""
        if not self._demand_paging:
            return
        pages = self._row_pages[slot]
        keep = new_pos // self.page_size + 1
        if len(pages) <= keep:
            return
        tail = pages[keep:]
        del pages[keep:]
        self._tables[slot, keep:keep + len(tail)] = 0
        self.page_pool.release(tail)
        self.spec_pages_rolled_back += len(tail)

    def _on_weights_swapped(self):
        # the reload-flush satellite: every cached page was computed
        # under the weights that just rotated out — a post-swap request
        # must miss (keys re-root on the new version too, belt and
        # braces). The swap only applies at a zero-in-flight boundary,
        # so the cache holds the only reference to every page and the
        # flush returns them all to the freelist.
        if self.prefix_cache is not None:
            self.prefix_cache.flush(reason="weights_reload")
        # up-call: speculation re-snapshots the self-spec draft and
        # invalidates old-weights draft caches
        super()._on_weights_swapped()

    # ---------------------------------------------------------- requests
    def _drop_block(self, blk):
        """Return a prefill block after a failed admission. Under
        donation the failed call may already have consumed the block's
        buffers — recycling would poison the freelist, so discard."""
        if blk is None:
            return
        if self._donate:
            self.pool.discard(blk)
        else:
            self.pool.free(blk)

    def _remote_prefill(self, req, bucket, key, trace=None):
        """Try the attached prefill pool: ``(first_token, flat_block)``
        on success, None when the transport is absent/down/failing (the
        caller runs local prefill — clean fallback, counted).
        ``trace`` is the admission's prefill span: the transport
        parents its wire span (and the worker's remote span) under
        it."""
        tr = self.prefill_transport
        if tr is None or not tr.available():
            return None
        from .fleet.kv_transfer import TransferError

        try:
            out = tr.prefill(
                [int(t) for t in req.input_ids], req.prompt_len, bucket,
                self.page_size, str(self.cache_dtype),
                float(self.temperature), key, trace=trace,
            )
        except TransferError:
            self.remote_prefill_fallbacks += 1
            return None
        self.remote_prefills += 1
        return out

    def _admit_one(self, handle):
        req = handle.request
        now = self.clock()
        ps = self.page_size
        bucket = self.pool.bucket_for(req.prompt_len)
        n_init = self._pages_at_admission(req.prompt_len,
                                          req.total_tokens)
        # sampling key drawn ONCE so a remote-prefill failure that falls
        # back locally consumes the same key the pure-local path would —
        # sampled streams stay reproducible either way (warm hits
        # consume it in the chunk program's sampling head)
        key = self._next_key()
        # prefix-cache walk: adopt matching full pages by reference and
        # recompute only the uncached tail. The fits predicate already
        # walked the chain for this pop — reuse its stashed probe
        # instead of matching twice per admission.
        match = plan = None
        if self.prefix_cache is not None:
            probe = req.__dict__.pop("_prefix_probe_result", None)
            if probe is None:
                probe = self._prefix_probe(req)
                req.__dict__.pop("_prefix_probe_result", None)
            match, plan = probe
            if match is not None:
                self.prefix_cache.hits.inc()
                self.prefix_cache.tokens_saved.inc(plan[0])
            else:
                self.prefix_cache.misses.inc()
        # the per-admission prefill span: mode (remote|local|fallback|
        # chunk) plus the prefix-hit/chunk-plan attributes the warm
        # path decided on — None (zero allocations) when sampled out
        psp = None if handle.trace is None else get_tracer().start_span(
            "engine.prefill", handle.trace, bucket=bucket,
            prefix_hit=match is not None,
        )
        if psp is not None and plan is not None:
            psp.set(chunk_start=plan[0], tail_bucket=plan[1],
                    cached_tokens=plan[0])
        fb0 = self.remote_prefill_fallbacks
        remote = None
        blk = None
        if match is None:
            remote = self._remote_prefill(req, bucket, key, trace=psp)
            if remote is None:
                ids = np.zeros((1, bucket), np.int32)
                ids[0, : req.prompt_len] = req.input_ids
                blk = self.pool.alloc(req.prompt_len)
        if psp is not None:
            psp.set(mode=(
                "chunk" if match is not None
                else "remote" if remote is not None
                else "fallback" if self.remote_prefill_fallbacks > fb0
                else "local"
            ))
        n_ref = 0 if match is None else plan[0] // ps
        ref_pages = [] if match is None else match.pages[:n_ref]
        row = None
        owned = []
        try:
            if n_ref:
                # reference the shared pages BEFORE any claim: claiming
                # may evict, and eviction must see these as in-use
                self.page_pool.incref(ref_pages)
                owned.extend(ref_pages)
            fresh = self._claim_pages(n_init - n_ref)
            owned.extend(fresh)
            row = self._free_rows.pop()
            row_pages = ref_pages + fresh
            self._tables[row, :] = 0
            self._tables[row, :n_init] = row_pages
            if match is not None:
                c, tb = plan
                L = req.prompt_len - c
                n_gather = -(-c // ps)
                src = np.zeros((bucket // ps,), np.int32)
                src[:n_gather] = match.pages[:n_gather]
                gsp = None if psp is None else get_tracer().start_span(
                    "engine.gather", psp, pages=n_gather
                )
                with profiler.RecordEvent(f"serving::gather_b{bucket}"):
                    flat_block = self._run(
                        ("gather", bucket), self._gather_fn(bucket),
                        self._flat, jnp.asarray(src),
                    )
                if gsp is not None:
                    gsp.finish()
                tail = np.zeros((1, tb), np.int32)
                tail[0, :L] = req.input_ids[c:]
                self.chunk_prefills += 1
                with profiler.RecordEvent(
                    f"serving::chunk_prefill_b{bucket}_t{tb}"
                ):
                    nxt, new_flat = self._run(
                        ("chunk", bucket, tb),
                        self._chunk_fn(bucket, tb),
                        self._params, self._buffers, jnp.asarray(tail),
                        jnp.int32(L), jnp.int32(c), flat_block,
                        jnp.float32(self.temperature), key,
                    )
                t0 = int(np.asarray(nxt)[0])
                if c % ps:
                    # recompute boundary inside a cached page: its
                    # content was cloned through the gather into a
                    # fresh page this request owns — the copy-on-write
                    # (the shared original is never written)
                    self.prefix_cache.cow_clones.inc()
            elif remote is None:
                self.local_prefills += 1
                with profiler.RecordEvent(f"serving::prefill_b{bucket}"):
                    nxt, new_flat = self._run(
                        ("prefill", bucket), self._prefill_fn(bucket),
                        self._params, self._buffers, jnp.asarray(ids),
                        jnp.int32(req.prompt_len), _flatten(blk.caches),
                        jnp.float32(self.temperature), key,
                    )
                    blk.caches = _unflatten(new_flat)
                    t0 = int(np.asarray(nxt)[0])
            else:
                # the prefill pool already ran the bucket program; the
                # wire block adopts through the SAME compiled scatter
                t0, new_flat = remote
            if psp is not None:
                psp.finish()
            asp = None if handle.trace is None else \
                get_tracer().start_span("engine.adopt", handle.trace,
                                        bucket=bucket)
            with profiler.RecordEvent(f"serving::adopt_b{bucket}"):
                # adopt: the request's FRESH pages within the bucket
                # span land in the claim; shared by-reference pages
                # (indices < n_ref) and block pad pages scatter to
                # garbage page 0 — a shared page is never written
                page_ids = np.zeros((bucket // ps,), np.int32)
                k1 = min(n_init, bucket // ps)
                page_ids[n_ref:k1] = row_pages[n_ref:k1]
                self._flat = self._run(
                    ("adopt", bucket), self._adopt_fn(bucket),
                    self._flat, new_flat, jnp.asarray(page_ids),
                )
            if asp is not None:
                asp.finish()
            if self.prefix_cache is not None:
                # publish-on-admission: full prompt pages are stable
                # the moment prefill wrote them (decode writes start at
                # prompt_len, past every full prompt page) — concurrent
                # same-prefix requests hit immediately
                self.prefix_cache.publish(
                    req.input_ids, req.prompt_len, row_pages,
                    self.weights_version,
                )
                self.prefix_cache.update_gauges()
        except BaseException:
            if psp is not None:
                psp.finish(error="admission_error")
            if row is not None:
                self._tables[row, :] = 0
                self._free_rows.append(row)
            if owned:
                self.page_pool.release(owned)
            self._drop_block(blk)
            raise
        if blk is not None:
            self.pool.free(blk)
        self._row_pages[row] = row_pages
        self._row_meta[row] = (
            tuple(int(t) for t in req.input_ids), req.prompt_len
        )
        handle.status = RUNNING
        handle.weights_version = self.weights_version
        handle.admit_time = now
        handle.admitted_step = self.step_count
        handle.first_token_time = self.clock()
        wait = now - handle.submit_time
        tid = None if handle.trace is None else handle.trace.trace_id
        self.metrics.admitted.inc()
        self.metrics.prefill_tokens.inc(req.prompt_len)
        self.metrics.queue_wait.observe(wait, trace_id=tid)
        slo_ttft, slo_itl, slo_e2e = self.metrics.slo_children(
            req.slo_class
        )
        slo_ttft.observe(handle.first_token_time - handle.submit_time,
                         trace_id=tid)
        self._trace_admitted(handle, row, wait)
        self._seqs[row] = _Seq(handle, t0, key=np.asarray(key),
                               slo_itl=slo_itl, slo_e2e=slo_e2e)
        self._append(row, t0)

    # ------------------------------------------------------- AOT warmup
    def warmup(self, aot_cache=None, buckets=None):
        """Extend the base warmup with the prefix-cache warm path: the
        per-bucket gather-pages program and the per-(bucket,
        tail-bucket) chunked-prefill ladder. Without this the FIRST
        warm hit per shape paid one untracked compile mid-request (the
        PR 14 residual) — now the whole warm-path inventory compiles
        (or AOT-cache-loads) before READY, and the trace guard's
        ``serving::gather_pages`` / ``serving::chunk_prefill`` entries
        are recorded up front, so any LATER compile on those keys is a
        storm finding, not silence."""
        stats = super().warmup(aot_cache=aot_cache, buckets=buckets)
        if self.prefix_cache is None:
            return stats
        from ..jit import aot_cache as aot_mod

        cache = aot_mod.resolve(aot_cache)
        if buckets is None:
            buckets = self._warmup_buckets()
        try:
            for b in buckets:
                ps = self.page_size
                gargs = (self._flat,
                         jnp.zeros((b // ps,), jnp.int32))
                self._warm_one(
                    cache, f"gather_b{b}", ("gather", b),
                    self._gather_fn(b), gargs,
                    lambda comp, b=b: self._gather_fns
                    .__setitem__(b, comp), stats,
                )
                blk = self.pool.alloc(b)
                try:
                    flat = _flatten(blk.caches)
                    for tb in self._tail_buckets(b):
                        cargs = (
                            self._params, self._buffers,
                            jnp.zeros((1, tb), jnp.int32),
                            jnp.int32(1), jnp.int32(0), flat,
                            jnp.float32(self.temperature), self._key,
                        )
                        self._warm_one(
                            cache, f"chunk_b{b}_t{tb}",
                            ("chunk", b, tb), self._chunk_fn(b, tb),
                            cargs,
                            lambda comp, b=b, tb=tb: self._chunk_fns
                            .__setitem__((b, tb), comp), stats,
                            donate=(5,) if self._donate else (),
                        )
                finally:
                    self.pool.free(blk)
            if self.kv_tier is not None:
                # the tier's restore program: a single-page adopt at
                # bucket == page_size (already warmed when page_size
                # equals the smallest prompt bucket — _warm_one
                # dedups on the trace key)
                ps = self.page_size
                self._warm_one(
                    cache, f"adopt_b{ps}", ("adopt", ps),
                    self._adopt_fn(ps),
                    (self._flat, self._page_block(),
                     jnp.zeros((1,), jnp.int32)),
                    lambda comp: self._adopt_fns
                    .__setitem__(ps, comp), stats,
                    donate=(0,) if self._donate else (),
                )
        finally:
            # lowering traced the bodies — restore concrete weights
            self._restore_net_state()
        return stats

    # ------------------------------------------------------ decode loop
    def _grow_pages(self):
        """Demand growth: before the decode step, any row whose next
        write position crosses into an unallocated page claims one
        (evicting cold prefixes if needed). A claim that still fails
        sheds THAT request with ``pages_exhausted`` — partial tokens
        kept, terminal event fired, nobody else's pages touched."""
        ps = self.page_size
        for i, seq in enumerate(self._seqs):
            if seq is None:
                continue
            pages = self._row_pages[i]
            while seq.pos // ps >= len(pages):
                try:
                    new = self._claim_pages(1)
                except PagesExhausted:
                    self.metrics.sheds.inc(label=REASON_PAGES_EXHAUSTED)
                    self._finish(i, CANCELLED,
                                 reason=REASON_PAGES_EXHAUSTED)
                    break
                self._tables[i, len(pages)] = new[0]
                pages.append(new[0])

    def _decode_once(self):
        if self._demand_paging:
            self._grow_pages()
        super()._decode_once()

    def close(self):
        super().close()
        if self.prefix_cache is not None:
            self.prefix_cache.flush(reason="engine_closed")
        if self.prefill_transport is not None:
            self.prefill_transport.close()
        self._tables = None
        self._row_pages = [None] * self.max_batch_size
        self._row_meta = [None] * self.max_batch_size
