"""Streaming HTTP/SSE front-end over a serving engine — stdlib only.

The network surface the serving stack was missing: POST a request, get
the tokens back as a Server-Sent-Events stream while the engine
decodes. Built on the same ``http.server`` seam as
``observability.exporter.MetricsServer`` — no third-party server, one
import to put a model on a port.

Endpoints:

- ``POST /v1/generate`` — body ``{"input_ids": [...],
  "max_new_tokens": N, "eos_token_id"?, "priority"?, "deadline_s"?,
  "stream"? (default true)}``. Streaming responses are
  ``text/event-stream``::

      event: token
      data: {"index": 0, "token": 17}

      event: done
      data: {"status": "DONE", "tokens": [...], ...}

  A request that ends any other way — queue-bound deadline, engine
  close, slow consumer — ends the stream with a TERMINAL ``event:
  error`` carrying the machine-readable reason (never a silent hang;
  ``paddle_serving_stream_aborts_total{reason}`` counts each).
  Backpressure surfaces as HTTP status BEFORE the stream opens:
  429 queue_full, 413 too_long, 400 malformed/shape_mismatch,
  503 engine_closed. ``"stream": false`` blocks and returns one JSON
  body instead.
- ``GET /metrics`` — the process Prometheus exposition (wire-level
  TTFT/ITL land here as ``paddle_serving_wire_{ttft,itl}_seconds``,
  measured at write() time — queueing, serialization and socket
  included, the latency a user actually sees).
- ``GET /healthz`` — engine/pool/queue stats as JSON.

Threading model: the engine is NOT thread-safe, so exactly one driver
thread steps it; HTTP handler threads only (a) submit under the
frontend lock and (b) consume their request's event queue, which the
engine's per-token callbacks feed from the driver thread. A slow or
disconnected client therefore can never stall the decode loop — its
stream is aborted and counted instead.
"""
from __future__ import annotations

import collections
import json
import math
import os
import queue
import threading
import time

from ..observability import get_registry
from ..observability.exporter import prometheus_text
from ..observability.tracing import (
    TRACEPARENT_HEADER,
    get_tracer,
    parse_traceparent,
    trace_payload,
)
from .metrics import Counter, Histogram

# terminal abort reasons surfaced on streams (engine REASON_* strings
# pass through verbatim; these are the frontend-originated ones)
ABORT_CLIENT_DISCONNECT = "client_disconnect"
ABORT_STREAM_STALL = "stream_stall"
ABORT_FRONTEND_STOPPED = "frontend_stopped"

_STATUS_FOR_REASON = {
    "queue_full": 429,
    "too_long": 413,
    "shape_mismatch": 400,
    "engine_closed": 503,
    "draining": 503,
}


class FrontendMetrics:
    """Wire-level series, one instance per frontend (replace-on-register
    in the process registry, like ServingMetrics)."""

    def __init__(self, registry=None, namespace="paddle_serving"):
        ns = namespace
        self.wire_ttft = Histogram(
            "wire_ttft", prom_name=f"{ns}_wire_ttft_seconds",
            help="request-received to first token byte written")
        self.wire_itl = Histogram(
            "wire_itl", prom_name=f"{ns}_wire_itl_seconds",
            help="gap between consecutive token writes on one stream")
        self.stream_aborts = Counter(
            "stream_aborts", labelname="reason",
            prom_name=f"{ns}_stream_aborts_total",
            help="streams ended by a terminal error event, by reason")
        self.http_requests = Counter(
            "http_requests", labelname="code",
            prom_name=f"{ns}_http_requests_total",
            help="front-end HTTP responses, by status code")
        reg = registry or get_registry()
        reg.register_all([
            self.wire_ttft, self.wire_itl, self.stream_aborts,
            self.http_requests,
        ])


class ServingFrontend:
    """HTTP/SSE front-end driving one engine on a background thread.

    ``port=0`` binds an ephemeral port (read ``.port`` back). Works with
    :class:`~.engine.ServingEngine`, :class:`~.paged_engine.
    PagedServingEngine` and :class:`~.engine.StaticBatchEngine` — any
    engine with the submit/streaming-callback surface. The driver
    thread steps live engines; a StaticBatchEngine (batch-at-once saved
    artifact) is driven through ``run_until_idle`` per drained queue.
    """

    def __init__(self, engine, host="127.0.0.1", port=0, registry=None,
                 stream_timeout_s=120.0, slo_monitor=None):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.metrics = FrontendMetrics(registry=registry)
        self.stream_timeout_s = float(stream_timeout_s)
        # SLO observability plane: the monitor backs /alerts and the
        # healthz alerts block. A caller-provided monitor is used as-is
        # (the caller owns its sampling); otherwise one is created and
        # its background sampler starts with the frontend when
        # PADDLE_TPU_SLO_INTERVAL (seconds) is set.
        if slo_monitor is None:
            from ..observability.slo import SLOMonitor

            iv = os.environ.get("PADDLE_TPU_SLO_INTERVAL")
            slo_monitor = SLOMonitor(
                registry=registry,
                interval_s=float(iv) if iv else 5.0,
            )
            self._own_slo_monitor = bool(iv)
        else:
            self._own_slo_monitor = False
        self.slo_monitor = slo_monitor
        # graceful drain: a draining frontend stops ADMITTING (new
        # generate requests get 503 {"reason": "draining"}) but keeps
        # the driver stepping, so every in-flight stream finishes —
        # the router rotates a replica out with zero dropped requests
        self.draining = False
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._httpd = None
        self._http_thread = None
        self._driver_thread = None
        # (time, repr) of swallowed step errors — bounded so a
        # persistently failing step cannot grow memory without limit.
        self.driver_errors = collections.deque(maxlen=256)
        from ..analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)

    # ---------------------------------------------------------- lifecycle
    def start(self):
        from .httpd import start_http_server

        self._httpd, self._http_thread = start_http_server(
            self.host, self.port, self._handle_get, self._handle_post,
            name="paddle-serve-http",
        )
        self.port = self._httpd.server_address[1]
        self._driver_thread = threading.Thread(
            target=self._drive, name="paddle-serve-driver", daemon=True,
        )
        self._driver_thread.start()
        if self._own_slo_monitor:
            self.slo_monitor.start()
        return self

    def stop(self, close_engine=False):
        """Stop serving. Open streams get a terminal
        ``frontend_stopped``/engine-close error event rather than a
        hang (``close_engine=True`` cancels in-flight requests, which
        fires their terminal callbacks)."""
        self._stop.set()
        if self._own_slo_monitor:
            self.slo_monitor.stop()
        if close_engine:
            with self._lock:
                try:
                    self.engine.close()
                except Exception:
                    pass
        if self._driver_thread is not None:
            self._driver_thread.join(timeout=10)
            self._driver_thread = None
        from .httpd import stop_http_server

        stop_http_server(self._httpd, self._http_thread)
        self._httpd = None
        self._http_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------- driver
    def _engine_busy(self):
        depth = getattr(self.engine.scheduler, "depth", 0)
        active = getattr(self.engine, "active_slots", 0)
        return bool(depth or active)

    def _drive(self):
        stepper = getattr(self.engine, "step", None)
        while not self._stop.is_set():
            busy = False
            errored = False
            with self._lock:
                if self._engine_busy() and not getattr(
                    self.engine, "_closed", False
                ):
                    busy = True
                    try:
                        if stepper is not None:
                            stepper()
                        else:  # StaticBatchEngine: batch-at-once
                            self.engine.run_until_idle()
                    except Exception as e:  # a failed admission already
                        # resolved its handle; the loop must survive
                        errored = True
                        self.driver_errors.append(
                            (time.monotonic(), repr(e))
                        )
            if errored:
                # Back off: a persistently failing step() must not spin
                # a core at full speed while it keeps failing.
                time.sleep(0.005)
            elif not busy:
                time.sleep(0.001)

    # ----------------------------------------------------------- handlers
    def _send_json(self, h, code, obj):
        from .httpd import send_json

        send_json(h, code, obj)
        self.metrics.http_requests.inc(label=str(code))

    def _handle_get(self, h):
        from .httpd import send_text

        path = h.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                send_text(
                    h, 200, prometheus_text().encode("utf-8"),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.metrics.http_requests.inc(label="200")
            elif path == "/trace":
                self._send_json(h, 200, trace_payload())
            elif path == "/alerts":
                self._send_json(h, 200, self.slo_monitor.status())
            elif path == "/healthz":
                self._send_json(h, 200, self.health())
            else:
                self._send_json(h, 404, {"error": "not found"})
        except Exception as e:
            try:
                self._send_json(h, 500, {"error": repr(e)})
            except Exception:
                pass

    def health(self):
        """Machine-readable replica status — the routing-admission
        signal a fleet router scrapes, not just a liveness bit: free
        pages (capacity), queue depth + in-flight (pressure), engine
        generation/weights version (routing can pin a version during a
        rollout), and the draining/accepting flags.

        Deliberately lock-free (taking the driver lock would queue
        scrapes behind whole engine steps and age healthy replicas out
        of the router's rotation under load), so the pool/prefix-cache
        stats may race a driver-thread mutation mid-iteration — a
        transient "dict changed size"/KeyError is retried rather than
        500ing a healthy replica."""
        for _ in range(5):
            try:
                return self._health_snapshot()
            except (RuntimeError, KeyError):
                continue
        return self._health_snapshot()

    def _health_snapshot(self):
        eng = self.engine
        queue_depth = getattr(eng.scheduler, "depth", 0)
        active = getattr(eng, "active_slots", 0)
        closed = bool(getattr(eng, "_closed", False))
        out = {
            "queue_depth": queue_depth,
            "active": active,
            "in_flight": queue_depth + active,
            "closed": closed,
            "draining": bool(self.draining),
            "accepting": not closed and not self.draining,
            "engine": type(eng).__name__,
            "generation": getattr(eng, "generation", 0),
            "weights_version": getattr(eng, "weights_version", None),
            "last_reload_step": getattr(eng, "last_reload_step", None),
            "reload_in_progress": bool(
                getattr(eng, "reload_in_progress", False)
            ),
            "compile_cache_hits": getattr(eng, "compile_cache_hits", 0),
            "max_queue_size": getattr(eng.scheduler, "max_queue_size",
                                      None),
            # burn-rate alert block: what the fleet router aggregates —
            # a fleet-wide SLO breach is one /healthz scrape away
            "alerts": self.slo_monitor.alerts_block(),
        }
        guard = getattr(eng, "trace_guard", None)
        if guard is not None:
            # total compiled-program inventory: a warm-started replica
            # must show this number UNCHANGED across first traffic
            out["compile_entries"] = int(
                sum(guard.compile_counts().values())
            )
        pool = getattr(eng, "pool", None)
        if pool is not None:
            out["pool"] = pool.stats()
        page_pool = getattr(eng, "page_pool", None)
        if page_pool is not None:
            out["page_pool"] = page_pool.stats()
            out["free_pages"] = page_pool.free_pages
            prefix = getattr(eng, "prefix_cache", None)
            if prefix is not None:
                # warm-capacity signal for the fleet router: hit stats
                # drive the cache-affinity bonus in its load score
                out["prefix_cache"] = prefix.stats()
            tier = getattr(eng, "kv_tier", None)
            if tier is not None:
                # hierarchical KV tiering: spilled-page residency per
                # tier (host/disk) plus refusal counters — the capacity
                # story behind "resident sessions grow with host RAM"
                out["kv_tier"] = tier.stats()
        else:
            slab = getattr(eng, "_slab", None)
            if slab is not None:
                # slab rows are the closest capacity analogue
                out["free_pages"] = slab.free_slots
        sessions = getattr(eng, "sessions", None)
        if sessions is not None:
            # conversation bookkeeping: active-session count and
            # retirement breakdown (ttl vs lru)
            out["sessions"] = sessions.stats()
        spec = getattr(eng, "speculative", None)
        if spec is not None:
            # speculative decoding: acceptance stats plus the verify-
            # page accounting (transient demand-grown pages show in
            # page_pool.stats() while held; these counters prove the
            # rejected tails came back)
            out["speculative"] = spec.stats()
            out["speculative"]["pages_claimed"] = getattr(
                eng, "spec_pages_claimed", 0
            )
            out["speculative"]["pages_rolled_back"] = getattr(
                eng, "spec_pages_rolled_back", 0
            )
        transport = getattr(eng, "prefill_transport", None)
        if transport is not None:
            out["remote_prefill"] = {
                "available": transport.available(),
                "remote": getattr(eng, "remote_prefills", 0),
                "local": getattr(eng, "local_prefills", 0),
                "fallbacks": getattr(eng, "remote_prefill_fallbacks",
                                     0),
            }
        mem = getattr(eng, "memory_report", None)
        mem = mem() if callable(mem) else None
        if mem is not None:
            # the full warmed-program HBM footprint inventory (the
            # memory_lint live-range estimate per compiled program,
            # with XLA memory_analysis + drift where available)
            out["memory"] = mem
        return out

    def _handle_post(self, h):
        path = h.path.split("?", 1)[0]
        if path in ("/drain", "/undrain"):
            # rotate-out seam: stop admitting, finish in-flight, report
            # the moment the replica is idle via the status fields
            self.draining = path == "/drain"
            self._send_json(h, 200, self.health())
            return
        if path == "/reload":
            self._handle_reload(h)
            return
        if path != "/v1/generate":
            self._send_json(h, 404, {"error": "not found"})
            return
        if self.draining:
            self._send_json(
                h, 503, {"error": "rejected", "reason": "draining"}
            )
            return
        try:
            n = int(h.headers.get("Content-Length", 0))
            body = json.loads(h.rfile.read(n) or b"{}")
            ids = body["input_ids"]
            if not isinstance(ids, list) or not ids or not all(
                isinstance(t, int) for t in ids
            ):
                raise ValueError(
                    "input_ids must be a non-empty list of ints"
                )
            # Every optional field is coerced HERE so a malformed value
            # is a 400 on this request — a raw string deadline_s reaching
            # the scheduler heap would poison sweep_expired for everyone.
            kwargs = {}
            for k in ("eos_token_id", "priority"):
                if body.get(k) is not None:
                    kwargs[k] = int(body[k])
            if body.get("deadline_s") is not None:
                deadline_s = float(body["deadline_s"])
                if not math.isfinite(deadline_s) or deadline_s < 0:
                    raise ValueError(
                        "deadline_s must be a non-negative finite number"
                    )
                kwargs["deadline_s"] = deadline_s
            max_new = None
            if body.get("max_new_tokens") is not None:
                max_new = int(body["max_new_tokens"])
                if max_new < 1:
                    raise ValueError("max_new_tokens must be >= 1")
            # resolve the SLO class at the wire: unknown -> 400 right
            # here; absent -> the default class. Only an explicit field
            # is forwarded to submit (an engine without the kwarg —
            # user-supplied stub — still takes default-class traffic).
            from ..observability.slo import DEFAULT_CLASS, get_slo_registry

            slo_class = DEFAULT_CLASS
            if body.get("slo_class") is not None:
                raw = body["slo_class"]
                if not isinstance(raw, str):
                    raise ValueError("slo_class must be a string")
                slo_class = get_slo_registry().validate(raw)
                kwargs["slo_class"] = slo_class
            # conversation identity: forwarded only when present so a
            # session-less engine (user-supplied stub without the
            # kwarg) still takes plain traffic unchanged
            if body.get("session_id") is not None:
                sid = body["session_id"]
                if not isinstance(sid, str) or not sid:
                    raise ValueError(
                        "session_id must be a non-empty string"
                    )
                kwargs["session_id"] = sid
        except Exception as e:
            self._send_json(h, 400, {"error": f"bad request: {e}"})
            return
        stream = bool(body.get("stream", True))
        events = queue.Queue()  # bounded by max_new_tokens + 1

        def on_token(tok, handle):
            events.put(("token", tok))

        def on_event(handle):
            events.put(("end", handle))

        submit_args = ([[int(t) for t in ids]],)
        if max_new is not None and hasattr(self.engine, "max_seq_len"):
            submit_args = submit_args + (max_new,)
        t_recv = time.monotonic()
        # an upstream router's traceparent makes this a child server
        # span; a direct request starts a new (head-sampled) root
        ctx = parse_traceparent(h.headers.get(TRACEPARENT_HEADER))
        tr = get_tracer()
        try:
            with self._lock:
                handle = self.engine.submit(
                    *submit_args, on_token=on_token, on_event=on_event,
                    **kwargs,
                )
                # under the SAME lock the driver steps with: the engine
                # cannot admit this handle before its trace is attached
                if not handle.finished:
                    if ctx is not None:
                        handle.trace = tr.start_span(
                            "frontend.request", ctx,
                            request_id=handle.request.request_id,
                            prompt_len=handle.request.prompt_len,
                            slo_class=slo_class,
                        )
                    else:
                        handle.trace = tr.start_trace(
                            "frontend.request",
                            request_id=handle.request.request_id,
                            prompt_len=handle.request.prompt_len,
                            slo_class=slo_class,
                        )
        except TypeError as e:
            # a field the wrapped engine doesn't take (StaticBatchEngine
            # has no eos_token_id) is the client's problem — 400, never
            # a dropped connection
            self._send_json(h, 400, {"error": f"bad request: {e}"})
            return
        except Exception as e:
            self._send_json(h, 500, {"error": repr(e)})
            return
        if handle.status == "REJECTED":
            code = _STATUS_FOR_REASON.get(handle.reason, 400)
            self._send_json(
                h, code,
                {"error": "rejected", "reason": handle.reason},
            )
            return
        if stream:
            self._stream_response(h, handle, events, t_recv)
        else:
            self._blocking_response(h, handle, events)
        if handle.trace is not None:
            handle.trace.finish(status=handle.status,
                                tokens=len(handle.tokens))

    def _handle_reload(self, h):
        """Live weight reload over the wire: heavy work (disk reads,
        CRC verify, quantization) runs on THIS handler thread with no
        lock held — the driver keeps decoding; only the commit takes
        the lock. 200 = staged or applied, 409 = refused (torn/
        incompatible checkpoint; the engine keeps its weights)."""
        eng = self.engine
        try:
            n = int(h.headers.get("Content-Length", 0))
            body = json.loads(h.rfile.read(n) or b"{}")
            ckpt_dir = body["ckpt_dir"]
            if not isinstance(ckpt_dir, str) or not ckpt_dir:
                raise ValueError("ckpt_dir must be a non-empty string")
            version = body.get("weights_version")
        except Exception as e:
            self._send_json(h, 400, {"error": f"bad request: {e}"})
            return
        if not hasattr(eng, "prepare_reload"):
            self._send_json(h, 400, {
                "error": f"{type(eng).__name__} does not support live "
                         f"reload"})
            return
        try:
            staged = eng.prepare_reload(
                ckpt_dir, weights_version=version
            )
            if staged.ok:
                with self._lock:
                    eng.commit_reload(staged)
        except Exception as e:
            self._send_json(h, 500, {"error": repr(e)})
            return
        out = staged.to_json()
        out["applied"] = staged.applied
        out["health"] = self.health()
        self._send_json(h, 200 if staged.ok else 409, out)

    def _terminal_payload(self, handle):
        return {
            "status": handle.status,
            "reason": handle.reason,
            "tokens": list(handle.tokens),
            "prompt_len": handle.request.prompt_len,
            "ttft_s": handle.ttft,
            "weights_version": getattr(handle, "weights_version", None),
        }

    def _blocking_response(self, h, handle, events):
        deadline = time.monotonic() + self.stream_timeout_s
        while time.monotonic() < deadline:
            try:
                kind, payload = events.get(timeout=1.0)
            except queue.Empty:
                if self._stop.is_set():
                    break
                continue
            if kind == "end":
                p = self._terminal_payload(handle)
                code = 200 if handle.status == "DONE" else (
                    _STATUS_FOR_REASON.get(handle.reason, 500)
                )
                # no stream_aborts sample here: stream_aborts counts SSE
                # streams ended by a terminal error event, and a
                # "stream": false request never opened one — the outcome
                # is fully visible in the HTTP status
                self._send_json(h, code, p)
                return
        reason = (ABORT_FRONTEND_STOPPED if self._stop.is_set()
                  else ABORT_STREAM_STALL)
        self._send_json(h, 504, {"error": reason})

    def _stream_response(self, h, handle, events, t_recv):
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Connection", "close")
        h.end_headers()
        self.metrics.http_requests.inc(label="200")

        def write_event(event, payload):
            h.wfile.write(
                f"event: {event}\ndata: {json.dumps(payload)}\n\n"
                .encode("utf-8")
            )
            h.wfile.flush()

        idx = 0
        last_write = None
        counted_abort = False
        tid = None if handle.trace is None else handle.trace.trace_id
        ssp = None if handle.trace is None else get_tracer().start_span(
            "frontend.stream", handle.trace
        )
        # poll in short slices so frontend stop() ends open streams
        # promptly instead of after a full stream_timeout_s of silence
        stall_at = time.monotonic() + self.stream_timeout_s
        try:
            while True:
                try:
                    kind, payload = events.get(timeout=0.25)
                except queue.Empty:
                    if self._stop.is_set():
                        reason = ABORT_FRONTEND_STOPPED
                    elif time.monotonic() >= stall_at:
                        reason = ABORT_STREAM_STALL
                    else:
                        continue
                    counted_abort = True
                    self.metrics.stream_aborts.inc(label=reason,
                                                   trace_id=tid)
                    if ssp is not None:
                        ssp.finish(tokens=idx, error=reason)
                    write_event("error", {"reason": reason,
                                          "status": handle.status})
                    return
                stall_at = time.monotonic() + self.stream_timeout_s
                if kind == "token":
                    write_event("token", {"index": idx,
                                          "token": int(payload)})
                    now = time.monotonic()
                    if idx == 0:
                        self.metrics.wire_ttft.observe(now - t_recv,
                                                       trace_id=tid)
                    elif last_write is not None:
                        self.metrics.wire_itl.observe(now - last_write)
                    last_write = now
                    idx += 1
                else:  # terminal — exactly once by the handle contract
                    p = self._terminal_payload(handle)
                    if handle.status == "DONE":
                        if ssp is not None:
                            ssp.finish(tokens=idx)
                        write_event("done", p)
                    else:
                        # the satellite fix: shed/expired requests END
                        # the open stream with the reject reason instead
                        # of hanging it
                        counted_abort = True
                        reason = (handle.reason
                                  or handle.status.lower())
                        self.metrics.stream_aborts.inc(label=reason,
                                                       trace_id=tid)
                        if ssp is not None:
                            ssp.finish(tokens=idx, error=reason)
                        write_event("error", p)
                    return
        except (BrokenPipeError, ConnectionResetError, OSError):
            # an abort counted just before its error-event write failed
            # must not produce a second client_disconnect sample
            if not counted_abort:
                self.metrics.stream_aborts.inc(
                    label=ABORT_CLIENT_DISCONNECT, trace_id=tid,
                )
            if ssp is not None:
                ssp.finish(tokens=idx, error=ABORT_CLIENT_DISCONNECT)


# --------------------------------------------------------- client helpers
def read_sse_events(fp):
    """Parse an SSE byte stream (a ``http.client`` response file) into
    ``(event, data_dict)`` pairs — the client half the bench, the smoke
    gate and the tests share."""
    event, data = None, []
    for raw in fp:
        line = raw.decode("utf-8").rstrip("\n")
        if not line:
            if event is not None:
                yield event, json.loads("\n".join(data) or "null")
            event, data = None, []
            continue
        if line.startswith(":"):
            continue  # comment/keepalive
        if line.startswith("event:"):
            event = line[6:].strip()
        elif line.startswith("data:"):
            data.append(line[5:].strip())
    if event is not None and data:
        yield event, json.loads("\n".join(data))


def stream_generate(host, port, payload, timeout=300.0):
    """POST ``payload`` to ``/v1/generate`` and consume the SSE stream.

    Returns ``(events, timings)`` where ``events`` is the parsed
    ``(event, data)`` list and ``timings`` carries client-measured
    ``ttft_s`` / per-gap ``itl_s`` (wire latency as the CLIENT sees it —
    serve_bench reports these next to the engine's in-process numbers).
    Raises ``HTTPRejected`` with ``.code``/``.body`` on a non-200."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    t0 = time.monotonic()
    conn.request(
        "POST", "/v1/generate", body=json.dumps(payload),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    if resp.status != 200:
        body = resp.read().decode("utf-8", "replace")
        conn.close()
        err = HTTPRejected(f"HTTP {resp.status}: {body}")
        err.code = resp.status
        try:
            err.body = json.loads(body)
        except Exception:
            err.body = {"raw": body}
        raise err
    events, itl, ttft, last = [], [], None, None
    for event, data in read_sse_events(resp):
        now = time.monotonic()
        if event == "token":
            if ttft is None:
                ttft = now - t0
            elif last is not None:
                itl.append(now - last)
            last = now
        events.append((event, data))
        if event in ("done", "error"):
            break
    conn.close()
    return events, {"ttft_s": ttft, "itl_s": itl}


class HTTPRejected(RuntimeError):
    """Non-200 response from the front-end; ``.code`` and ``.body``."""
