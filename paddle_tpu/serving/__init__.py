"""paddle_tpu.serving — continuous-batching LLM inference.

The layer that turns "can run a model" into "can serve a model": a
host-side request loop over compiled fixed-shape prefill/decode-step
programs (``engine.ServingEngine``), a bucketed KV-cache pool with bf16
default and occupancy accounting (``kv_pool.KVCachePool``), bounded
priority+FIFO admission with backpressure and deadlines
(``scheduler.Scheduler``), and serving metrics exported through
``paddle_tpu.profiler`` (``metrics.ServingMetrics``). Saved
``jit.save`` decode artifacts serve through the same request surface
via ``inference.Predictor.into_engine()``.

The paged runtime (``paged_pool.PagedKVPool`` +
``paged_engine.PagedServingEngine``) replaces the decode slab with a
page arena: a request claims ``ceil(total_tokens / page_size)`` pages
through a per-row page table, so resident KV HBM scales with actual
lengths and a mixed-length workload admits strictly more concurrent
requests at equal budget. Every engine carries per-token streaming
callbacks (``submit(..., on_token=, on_event=)``, terminal event
exactly once), and ``http_frontend.ServingFrontend`` puts any engine
on a port as a stdlib-only HTTP/SSE server (POST submit -> SSE token
stream, backpressure as HTTP status, wire-level TTFT/ITL metrics).

Above one engine sits the fleet tier (``fleet/``): a
``FleetRouter`` places requests across N replica processes by
health/occupancy (scraped replica status, circuit breaking, bounded
retry of unstarted requests, shed-with-reason), and a
``PrefillWorker``/``RemotePrefillClient`` pair disaggregates prefill
from decode ACROSS processes — finished KV pages ship over a
CRC-checked socket and adopt bit-identically to local prefill, with
clean local fallback.

The session KV runtime (``sessions.SessionStore`` +
``kv_tiering.TieredPageStore``) serves conversations, not requests: a
``session_id`` on submit threads chat turns into one identity with
TTL/LRU retirement, finished requests publish their decode-written
pages into the prefix cache (bitwise-equal to what re-prefilling
those tokens would write — the quantizer's bf16-grid scales pin this
for int8 too), and refcount-0 prefix pages spill to host RAM/disk as
CRC-checked PKV2 frames instead of being dropped, restoring
bit-identically on the next hit. Warm turn-N+1 prefill therefore
reuses turn N's full KV including the generated answer, and resident
conversational state scales with host memory at fixed HBM.

Speculative decoding (``speculative.SpeculativeDecoder``) pairs a
small draft (or the target's own early-exit layers) with either
engine: the draft proposes K tokens, ONE batched target launch
verifies them, and acceptance keeps greedy streams EXACT-EQUAL to
vanilla decode (rejection sampling keeps sampled streams
distribution-equal) — rounds emit 1..K+1 tokens per verify launch.

Everything is pure Python + JAX and CPU-testable;
``tools/serve_bench.py`` replays a synthetic Poisson trace offline
(``--http`` drives real SSE streams over localhost; ``--fleet N``
spawns replica subprocesses behind the router) and reports
throughput/latency percentiles; ``make serve-smoke`` and
``make fleet-smoke`` gate the HTTP and cluster paths end to end.
"""
from . import chaos  # noqa: F401
from .engine import ServingEngine, StaticBatchEngine  # noqa: F401
from .fleet import (  # noqa: F401
    FleetRouter,
    PrefillWorker,
    RemotePrefillClient,
    TransferError,
)
from .http_frontend import (  # noqa: F401
    FrontendMetrics,
    HTTPRejected,
    ServingFrontend,
    read_sse_events,
    stream_generate,
)
from .kv_pool import (  # noqa: F401
    KVBlock,
    KVCachePool,
    PoolExhausted,
    bucket_for,
)
from .kv_tiering import (  # noqa: F401
    TIER_DISK,
    TIER_HOST,
    TieredPageStore,
    pack_page,
    unpack_page,
)
from .metrics import Counter, Histogram, ServingMetrics  # noqa: F401
from .paged_engine import PagedServingEngine  # noqa: F401
from .paged_pool import PagedKVPool, PagesExhausted  # noqa: F401
from .prefix_cache import PrefixCache, PrefixMatch  # noqa: F401
from .reload import ReloadError, StagedReload  # noqa: F401
from .sampling_keys import SamplingKeySource  # noqa: F401
from .scheduler import (  # noqa: F401
    REASON_ENGINE_CLOSED,
    REASON_PAGES_EXHAUSTED,
    REASON_QUEUE_FULL,
    REASON_SHAPE_MISMATCH,
    REASON_TIMEOUT,
    REASON_TOO_LONG,
    RejectedError,
    Request,
    RequestHandle,
    Scheduler,
)
from .sessions import Session, SessionStore  # noqa: F401
from .speculative import SpeculativeDecoder  # noqa: F401
