"""paddle_tpu.serving — continuous-batching LLM inference.

The layer that turns "can run a model" into "can serve a model": a
host-side request loop over compiled fixed-shape prefill/decode-step
programs (``engine.ServingEngine``), a bucketed KV-cache pool with bf16
default and occupancy accounting (``kv_pool.KVCachePool``), bounded
priority+FIFO admission with backpressure and deadlines
(``scheduler.Scheduler``), and serving metrics exported through
``paddle_tpu.profiler`` (``metrics.ServingMetrics``). Saved
``jit.save`` decode artifacts serve through the same request surface
via ``inference.Predictor.into_engine()``. Everything is pure
Python + JAX and CPU-testable; ``tools/serve_bench.py`` replays a
synthetic Poisson trace offline and reports throughput/latency
percentiles.
"""
from .engine import ServingEngine, StaticBatchEngine  # noqa: F401
from .kv_pool import (  # noqa: F401
    KVBlock,
    KVCachePool,
    PoolExhausted,
    bucket_for,
)
from .metrics import Counter, Histogram, ServingMetrics  # noqa: F401
from .scheduler import (  # noqa: F401
    REASON_ENGINE_CLOSED,
    REASON_QUEUE_FULL,
    REASON_SHAPE_MISMATCH,
    REASON_TIMEOUT,
    REASON_TOO_LONG,
    RejectedError,
    Request,
    RequestHandle,
    Scheduler,
)
