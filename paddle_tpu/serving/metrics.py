"""Serving metrics: counters + histograms with profiler export.

The serving quantities users actually page on — queue depth,
time-to-first-token, inter-token latency, slot occupancy, rejection and
timeout counts — live here as plain host-side counters/histograms (no
device work; observing a sample is a list append). Every histogram
sample is ALSO forwarded to ``paddle_tpu.profiler.record_span`` under a
``serving::`` prefix, so when a ``profiler.Profiler`` RECORD window is
open the serving latencies appear in ``Profiler.summary()`` and the
chrome trace next to the op/user spans — one observability surface, not
two.
"""
from __future__ import annotations

import threading


class Counter:
    """Monotonic counter (optionally labeled by a reason string)."""

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._by_label = {}
        self._lock = threading.Lock()

    def inc(self, n=1, label=None):
        with self._lock:
            self._value += n
            if label is not None:
                self._by_label[label] = self._by_label.get(label, 0) + n

    @property
    def value(self):
        return self._value

    def by_label(self):
        with self._lock:
            return dict(self._by_label)


class Histogram:
    """Sample store with percentile readout.

    Memory-bounded for long-running servers: the window keeps the most
    recent ``maxlen`` samples (sliding-window percentiles — what a
    latency dashboard wants anyway), while ``count``/``sum`` stay exact
    running totals over ALL observations."""

    def __init__(self, name, unit="s", export=True, maxlen=65536):
        import collections

        self.name = name
        self.unit = unit
        self._samples = collections.deque(maxlen=int(maxlen))
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()
        self._export = export

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
        if self._export:
            from .. import profiler

            profiler.record_span(f"serving::{self.name}", v)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, p):
        """p in [0, 100]; nearest-rank. None when empty."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        k = max(0, min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    def snapshot(self):
        # copy under the lock: a shared ServingMetrics may be observed
        # from an engine thread while another thread reports
        with self._lock:
            if not self._samples:
                return {"count": 0}
            window = sorted(self._samples)
            count, total = self._count, self._sum

        def pct(p):
            k = max(0, min(len(window) - 1,
                           int(round(p / 100.0 * (len(window) - 1)))))
            return window[k]

        return {
            "count": count,
            "sum": total,
            "mean": total / count,
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
            "max": window[-1],
            "min": window[0],
            "unit": self.unit,
        }


class ServingMetrics:
    """The engine's metric set. One instance per engine (or share one
    across engines to aggregate a process)."""

    def __init__(self):
        self.submitted = Counter("submitted")
        self.admitted = Counter("admitted")
        self.completed = Counter("completed")
        self.rejected = Counter("rejected")      # labeled by reason
        self.timeouts = Counter("timeouts")
        self.tokens_out = Counter("tokens_out")
        self.prefill_tokens = Counter("prefill_tokens")
        self.guard_fires = Counter("guard_fires")  # labeled by fn key
        self.ttft = Histogram("ttft")            # submit -> first token
        self.itl = Histogram("itl")              # inter-token latency
        self.e2e = Histogram("e2e")              # submit -> finished
        self.queue_wait = Histogram("queue_wait")  # submit -> admitted
        self.queue_depth = Histogram("queue_depth", unit="reqs",
                                     export=False)
        self.slot_occupancy = Histogram("slot_occupancy", unit="slots",
                                        export=False)

    def observe_step(self, queue_depth, active_slots):
        self.queue_depth.observe(queue_depth)
        self.slot_occupancy.observe(active_slots)

    def report(self):
        """Plain-dict snapshot (what serve_bench prints as JSON)."""
        return {
            "counters": {
                "submitted": self.submitted.value,
                "admitted": self.admitted.value,
                "completed": self.completed.value,
                "rejected": self.rejected.value,
                "rejected_by_reason": self.rejected.by_label(),
                "timeouts": self.timeouts.value,
                "tokens_out": self.tokens_out.value,
                "prefill_tokens": self.prefill_tokens.value,
                "guard_fires": self.guard_fires.value,
                "guard_fires_by_fn": self.guard_fires.by_label(),
            },
            "ttft": self.ttft.snapshot(),
            "itl": self.itl.snapshot(),
            "e2e": self.e2e.snapshot(),
            "queue_wait": self.queue_wait.snapshot(),
            "queue_depth": self.queue_depth.snapshot(),
            "slot_occupancy": self.slot_occupancy.snapshot(),
        }

    def render(self):
        """Human-readable table of the report."""
        r = self.report()
        lines = ["serving metrics", "-" * 15]
        for k, v in r["counters"].items():
            lines.append(f"{k:>20}: {v}")
        for name in ("ttft", "itl", "e2e", "queue_wait",
                     "queue_depth", "slot_occupancy"):
            s = r[name]
            if not s.get("count"):
                lines.append(f"{name:>20}: (no samples)")
                continue
            unit = s.get("unit", "s")
            scale = 1e3 if unit == "s" else 1.0
            u = "ms" if unit == "s" else unit
            lines.append(
                f"{name:>20}: n={s['count']} "
                f"p50={s['p50'] * scale:.3f}{u} "
                f"p90={s['p90'] * scale:.3f}{u} "
                f"p99={s['p99'] * scale:.3f}{u} "
                f"max={s['max'] * scale:.3f}{u}"
            )
        return "\n".join(lines)
