"""Serving metrics: registry-based counters + histograms with profiler export.

The serving quantities users actually page on — queue depth,
time-to-first-token, inter-token latency, slot occupancy, rejection and
timeout counts — live here as plain host-side counters/histograms (no
device work; observing a sample is a list append). Since the unified
telemetry PR these are thin subclasses of the process-wide
``paddle_tpu.observability`` instruments: every ServingMetrics
registers its set under ``paddle_serving_*`` names in the global
registry (replace-on-register — the newest engine's metrics own the
series), so one Prometheus scrape covers serving alongside training
and analysis telemetry. Every histogram sample is ALSO forwarded to
``paddle_tpu.profiler.record_span`` under a ``serving::`` prefix, so
when a ``profiler.Profiler`` RECORD window is open the serving
latencies appear in ``Profiler.summary()`` and the chrome trace next to
the op/user spans — one observability surface, not two.
"""
from __future__ import annotations

from ..observability import registry as _reg


class Counter(_reg.Counter):
    """Monotonic counter (optionally labeled by a reason string).

    The serving-side convenience shape over the registry Counter: one
    optional label dimension (``labelname``), ``by_label()`` readout."""

    def __init__(self, name, labelname="label", prom_name=None, help=""):
        super().__init__(name, help=help, prom_name=prom_name)
        self._labelname = labelname

    def inc(self, n=1, label=None, trace_id=None, **labels):
        """``label=`` is the serving shorthand for the configured
        labelname; registry-style ``**labels`` kwargs (what the
        inherited ``.labels()`` binding forwards) pass straight
        through, so both idioms work on the same instrument.
        ``trace_id`` records an exemplar on the bumped series."""
        if label is not None:
            labels[self._labelname] = label
        super().inc(n, trace_id=trace_id, **labels)

    def by_label(self):
        out = {}
        for k, v in self.series().items():
            d = dict(k)
            if self._labelname in d:
                out[d[self._labelname]] = \
                    out.get(d[self._labelname], 0) + v
        return out


class Histogram(_reg.Histogram):
    """Sample store with percentile readout + profiler span export.

    Memory-bounded for long-running servers: the window keeps the most
    recent ``maxlen`` samples (sliding-window percentiles — what a
    latency dashboard wants anyway), while ``count``/``sum``/Prometheus
    buckets stay exact running totals over ALL observations.
    ``snapshot()['mean']`` is the exact running ``sum/count``;
    p50/p90/p99/min/max describe only the window —
    ``snapshot()['window_count']`` tells dashboards how big that window
    population is (see the base class docstring for the full split)."""

    def __init__(self, name, unit="s", export=True, maxlen=65536,
                 prom_name=None, buckets=None, help=""):
        if buckets is None:
            buckets = (_reg.DEFAULT_BUCKETS if unit == "s"
                       else _reg.COUNT_BUCKETS)
        super().__init__(name, help=help, unit=unit, maxlen=maxlen,
                         buckets=buckets, prom_name=prom_name)
        self._export = export

    def observe(self, v, trace_id=None, labels_key=None):
        super().observe(float(v), trace_id=trace_id,
                        labels_key=labels_key)
        if self._export:
            from .. import profiler

            profiler.record_span(f"serving::{self.name}", float(v))


class ServingMetrics:
    """The engine's metric set. One instance per engine (or share one
    across engines to aggregate a process). Registered in the process
    registry under ``<namespace>_*`` with replace semantics: the most
    recently constructed instance owns the exported series."""

    def __init__(self, registry=None, namespace="paddle_serving"):
        ns = namespace
        self.submitted = Counter(
            "submitted", prom_name=f"{ns}_submitted_total",
            help="requests submitted")
        self.admitted = Counter(
            "admitted", prom_name=f"{ns}_admitted_total",
            help="requests admitted into the decode slab")
        self.completed = Counter(
            "completed", prom_name=f"{ns}_completed_total",
            help="requests finished DONE")
        self.rejected = Counter(          # labeled by reason
            "rejected", labelname="reason",
            prom_name=f"{ns}_rejected_total",
            help="requests rejected, by reason")
        self.timeouts = Counter(
            "timeouts", prom_name=f"{ns}_timeouts_total",
            help="requests expired past their deadline")
        self.sheds = Counter(             # labeled by reason
            "sheds", labelname="reason",
            prom_name=f"{ns}_sheds_total",
            help="in-flight requests shed by the engine, by reason "
                 "(pages_exhausted = a demand-grown decode page claim "
                 "that eviction could not satisfy)")
        self.tokens_out = Counter(
            "tokens_out", prom_name=f"{ns}_tokens_out_total",
            help="decode tokens emitted")
        self.prefill_tokens = Counter(
            "prefill_tokens", prom_name=f"{ns}_prefill_tokens_total",
            help="prompt tokens prefilled")
        self.guard_fires = Counter(       # labeled by fn key
            "guard_fires", labelname="fn",
            prom_name=f"{ns}_guard_fires_total",
            help="trace-guard recompile-storm fires seen by the engine")
        self.reloads = Counter(           # labeled by outcome
            "reloads", labelname="outcome",
            prom_name=f"{ns}_reloads_total",
            help="live weight reloads, by outcome (ok|verify_failed|"
                 "load_error|incompatible|error|...)")
        self.reload_ttft_spike = Histogram(
            "reload_ttft_spike",
            prom_name=f"{ns}_reload_ttft_spike_seconds",
            help="admission pause of one live reload (staged -> "
                 "applied): the worst-case extra TTFT a request queued "
                 "during the swap window saw")
        self.ttft = Histogram(            # submit -> first token
            "ttft", prom_name=f"{ns}_ttft_seconds",
            help="time to first token")
        self.itl = Histogram(             # inter-token latency
            "itl", prom_name=f"{ns}_itl_seconds",
            help="inter-token latency")
        self.e2e = Histogram(             # submit -> finished
            "e2e", prom_name=f"{ns}_e2e_seconds",
            help="end-to-end request latency")
        self.queue_wait = Histogram(      # submit -> admitted
            "queue_wait", prom_name=f"{ns}_queue_wait_seconds",
            help="queue wait before admission")
        self.queue_depth = Histogram(
            "queue_depth", unit="reqs", export=False,
            prom_name=f"{ns}_queue_depth",
            help="scheduler queue depth sampled per engine step")
        self.slot_occupancy = Histogram(
            "slot_occupancy", unit="slots", export=False,
            prom_name=f"{ns}_slot_occupancy",
            help="active decode-slab slots sampled per engine step")
        # speculative decoding (serving.speculative): one round = one
        # draft proposal pass + one target verify launch
        self.spec_rounds = Counter(
            "speculative_rounds",
            prom_name=f"{ns}_speculative_rounds_total",
            help="speculative propose+verify rounds run")
        self.spec_proposed = Counter(
            "speculative_proposed_tokens",
            prom_name=f"{ns}_speculative_proposed_tokens_total",
            help="draft tokens proposed to the verifier")
        self.spec_accepted = Counter(
            "speculative_accepted_tokens",
            prom_name=f"{ns}_speculative_accepted_tokens_total",
            help="draft tokens the verifier accepted")
        self.spec_accept_length = Histogram(
            "speculative_accept_length", unit="toks", export=False,
            prom_name=f"{ns}_speculative_accept_length",
            help="tokens emitted per speculative round (accepted "
                 "prefix + the correction/bonus token; mean > 1 is "
                 "the whole win)")
        reg = registry
        if reg is None:
            from ..observability import get_registry

            reg = get_registry()
        reg.register_all([
            self.submitted, self.admitted, self.completed, self.rejected,
            self.timeouts, self.sheds, self.tokens_out,
            self.prefill_tokens,
            self.guard_fires, self.reloads, self.reload_ttft_spike,
            self.ttft, self.itl, self.e2e,
            self.queue_wait, self.queue_depth, self.slot_occupancy,
            self.spec_rounds, self.spec_proposed, self.spec_accepted,
            self.spec_accept_length,
        ])
        # slo_class -> (ttft_child, itl_child, e2e_child). Lives on the
        # metrics OBJECT (not the engine) so the cache dies with the
        # instrument it binds to — serve_bench swaps engine.metrics
        # wholesale after warmup, and a cache held elsewhere would keep
        # observing into the discarded histograms.
        self._slo_children = {}

    def slo_children(self, slo_class):
        """Per-class bound children of the latency histograms, resolved
        once per class per metrics instance. Called at ADMISSION only;
        the returned bindings are what the hot loops observe into, so
        the per-token path never touches a label dict."""
        ch = self._slo_children.get(slo_class)
        if ch is None:
            ch = (
                self.ttft.labels(slo_class=slo_class),
                self.itl.labels(slo_class=slo_class),
                self.e2e.labels(slo_class=slo_class),
            )
            self._slo_children[slo_class] = ch
        return ch

    def observe_step(self, queue_depth, active_slots):
        self.queue_depth.observe(queue_depth)
        self.slot_occupancy.observe(active_slots)

    def report(self):
        """Plain-dict snapshot (what serve_bench prints as JSON)."""
        return {
            "counters": {
                "submitted": self.submitted.value,
                "admitted": self.admitted.value,
                "completed": self.completed.value,
                "rejected": self.rejected.value,
                "rejected_by_reason": self.rejected.by_label(),
                "timeouts": self.timeouts.value,
                "sheds": self.sheds.value,
                "sheds_by_reason": self.sheds.by_label(),
                "tokens_out": self.tokens_out.value,
                "prefill_tokens": self.prefill_tokens.value,
                "guard_fires": self.guard_fires.value,
                "guard_fires_by_fn": self.guard_fires.by_label(),
                "reloads": self.reloads.value,
                "reloads_by_outcome": self.reloads.by_label(),
                "speculative_rounds": self.spec_rounds.value,
                "speculative_proposed": self.spec_proposed.value,
                "speculative_accepted": self.spec_accepted.value,
            },
            "speculative_accept_length":
                self.spec_accept_length.snapshot(),
            "reload_ttft_spike": self.reload_ttft_spike.snapshot(),
            "ttft": self.ttft.snapshot(),
            "itl": self.itl.snapshot(),
            "e2e": self.e2e.snapshot(),
            "queue_wait": self.queue_wait.snapshot(),
            "queue_depth": self.queue_depth.snapshot(),
            "slot_occupancy": self.slot_occupancy.snapshot(),
        }

    def render(self):
        """Human-readable table of the report."""
        r = self.report()
        lines = ["serving metrics", "-" * 15]
        for k, v in r["counters"].items():
            lines.append(f"{k:>20}: {v}")
        for name in ("ttft", "itl", "e2e", "queue_wait",
                     "queue_depth", "slot_occupancy"):
            s = r[name]
            if not s.get("count"):
                lines.append(f"{name:>20}: (no samples)")
                continue
            unit = s.get("unit", "s")
            scale = 1e3 if unit == "s" else 1.0
            u = "ms" if unit == "s" else unit
            lines.append(
                f"{name:>20}: n={s['count']} "
                f"p50={s['p50'] * scale:.3f}{u} "
                f"p90={s['p90'] * scale:.3f}{u} "
                f"p99={s['p99'] * scale:.3f}{u} "
                f"max={s['max'] * scale:.3f}{u}"
            )
        return "\n".join(lines)
