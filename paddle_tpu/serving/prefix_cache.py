"""Hash-consed prefix page store — copy-on-write KV sharing for serving.

At production traffic most requests share a long prefix (system prompt,
few-shot header, RAG template); without this module every request
re-prefills that prefix and claims all of its pages privately. The
prefix cache layers content-addressed sharing on the
:class:`~.paged_pool.PagedKVPool` page arena:

- **Keys are hash chains at page granularity.** A published page is
  keyed by ``(parent_key, its page_size tokens)``, with the chain
  rooted at ``(weights_version, cache_dtype)`` — structurally collision
  -free (keys are the token tuples themselves, not digests), and a
  checkpoint rotation re-roots the whole keyspace so stale-weights KV
  can never match (the engine additionally flushes on swap).
- **Adoption is by reference.** A new request walks the chain and
  adopts every matching FULL page into its page table with a refcount
  (``pool.incref``); prefill then runs only on the uncached tail
  (``models.generation.prefill(pos=...)`` — the chunked prefill,
  tier-1-pinned bitwise-equal to the full-prompt program).
- **Copy-on-write.** When the recompute boundary lands inside a cached
  page (a divergent tail mid-page, or a fully-cached prompt whose last
  token must be re-run to produce logits), the shared page is CLONED
  through the gather -> chunk-prefill -> adopt pipeline into a fresh
  page the request owns; the shared original is never written
  (``cow_clones`` counts these).
- **Eviction is leaf-first LRU — and with a tier attached, eviction
  becomes SPILL.** Only pages whose sole reference is the cache's own
  (refcount 1) are evictable, and only entries whose cached
  descendants are themselves reclaimable — evicting a middle page
  would orphan its (still resident) children. Triggered by the engine
  under arena pressure; every reclaimed page counts. When a
  :class:`~.kv_tiering.TieredPageStore` is attached
  (:meth:`PrefixCache.attach_tier`), the victim's arena bytes are
  read out and stored as a CRC-checked host/disk payload before the
  HBM page is freed; :meth:`match` then consults the tier wherever
  its resident chain walk breaks and RESTORES the page through the
  engine's adopt program — a cold conversation costs a host->HBM copy
  instead of a full re-prefill, and a tier refusal (budget, CRC,
  stale weights) just degrades to the cold path.

Exactness is the contract, not a trade: cached KV for position ``p``
is a pure function of ``tokens[0..p]`` under fixed weights, and every
published page carries provenance for exactly the positions recorded
as valid — full prompt pages at admission, the partial prompt-tail
page at finish, and (since the session-KV PR) the DECODE-written span
at finish too: the decode step and the prefill program share one
masked-SDPA op order, pinned bitwise-equal in tier-1 for bf16 AND
int8 arenas, so a generated answer's KV is byte-for-byte what
re-prefilling those tokens would write. A warm request's token stream
is therefore pinned exact-equal to the cold path and to
``net.generate`` whether its prefix came from prefill, from decode,
or back out of a spill tier (restored bytes are pinned bit-identical
to the pre-spill arena page).
"""
from __future__ import annotations

import heapq
import itertools


class PrefixEntry:
    """One cached page: its chain key, the arena page holding its KV,
    the tokens it covers, and how many leading slots carry
    prefill-provenance content (``valid_len < page_size`` for the
    partial prompt-tail page published at finish)."""

    __slots__ = ("key", "parent", "page", "tokens", "valid_len",
                 "last_hit")

    def __init__(self, key, parent, page, tokens, valid_len, tick):
        self.key = key
        self.parent = parent
        self.page = int(page)
        self.tokens = tuple(int(t) for t in tokens)
        self.valid_len = int(valid_len)
        self.last_hit = tick

    @property
    def full(self):
        return self.valid_len == len(self.tokens)

    def __repr__(self):
        return (f"PrefixEntry(page={self.page}, "
                f"tokens={len(self.tokens)}, valid={self.valid_len})")


class PrefixMatch:
    """Result of one chain walk: the full-page entries matched in
    order, an optional partial-tail entry covering the rest of the
    prompt, and the covered token count."""

    __slots__ = ("entries", "tail", "covered")

    def __init__(self, entries, tail, covered):
        self.entries = entries
        self.tail = tail
        self.covered = int(covered)

    @property
    def pages(self):
        """Matched arena page ids, chain order (tail last when hit)."""
        out = [e.page for e in self.entries]
        if self.tail is not None:
            out.append(self.tail.page)
        return out


class PrefixCache:
    """Content-addressed page store over one :class:`PagedKVPool`.

    The cache holds ONE pool reference per published page; requests
    adopting a page hold their own (the engine increfs at admission and
    releases at finish). A page is evictable only while the cache's
    reference is the last one. All methods are driver-thread-only, like
    the engine that owns it."""

    def __init__(self, pool, *, registry=None,
                 namespace="paddle_serving"):
        self.pool = pool
        self.page_size = int(pool.page_size)
        self._entries = {}    # key -> PrefixEntry
        self._children = {}   # parent key -> set of child keys
        self._tick = itertools.count()
        self.flushes = 0
        # spill tier (kv_tiering.TieredPageStore) + the engine-supplied
        # closures that move page bytes across the HBM boundary
        self._tier = None
        self._read_page = None
        self._restore_page = None
        self._current_version = None
        ns = namespace
        # per-INSTANCE instruments with replace-on-register, like
        # ServingMetrics: the newest cache owns the exported series and
        # each engine's stats()/healthz report ITS OWN traffic, not
        # process-lifetime totals across rebuilt engines
        from ..observability import Gauge
        from .metrics import Counter

        self.hits = Counter(
            "prefix_hits", prom_name=f"{ns}_prefix_hits_total",
            help="admissions that adopted at least one cached prefix "
                 "page")
        self.misses = Counter(
            "prefix_misses", prom_name=f"{ns}_prefix_misses_total",
            help="admissions that found no usable cached prefix")
        self.evictions = Counter(
            "prefix_evictions",
            prom_name=f"{ns}_prefix_evictions_total",
            help="cached prefix pages reclaimed under arena pressure")
        self.cow_clones = Counter(
            "prefix_cow_clones",
            prom_name=f"{ns}_prefix_cow_clones_total",
            help="shared pages copy-on-write cloned for a divergent "
                 "tail")
        self.tokens_saved = Counter(
            "prefix_tokens_saved",
            prom_name=f"{ns}_prefix_tokens_saved_total",
            help="prompt tokens NOT re-prefilled thanks to cache hits")
        self.hbm_saved = Gauge(
            "prefix_shared_hbm_saved",
            prom_name=f"{ns}_prefix_shared_hbm_saved_bytes",
            help="arena bytes saved by page sharing: pages that would "
                 "be private copies without the prefix cache")
        if registry is None:
            from ..observability import get_registry

            registry = get_registry()
        registry.register_all([
            self.hits, self.misses, self.evictions, self.cow_clones,
            self.tokens_saved, self.hbm_saved,
        ])

    # ---------------------------------------------------------- keying
    def root_key(self, weights_version):
        return ("prefix-root", str(weights_version),
                str(self.pool.dtype))

    # ----------------------------------------------------------- tiering
    def attach_tier(self, tier, *, read_page, restore_page,
                    current_version):
        """Attach a :class:`~.kv_tiering.TieredPageStore` below this
        cache. ``read_page(page_id)`` returns the page's host arrays
        (spill side); ``restore_page(arrays)`` claims a fresh arena
        page, adopts the bytes, and returns its id (or None when the
        arena cannot spare one — the record stays spilled);
        ``current_version()`` is the engine's live weights version,
        stamped into every spilled payload for the stale-refusal
        check. The engine wires these at construction."""
        self._tier = tier
        self._read_page = read_page
        self._restore_page = restore_page
        self._current_version = current_version

    def _restore(self, child_key, parent, weights_version):
        """Pull one spilled page back into the arena as a live cache
        entry, or None (absent / refused / arena full). The tier
        record is consumed BEFORE the entry lands so a later publish
        of the same key never races a stale payload."""
        tier = self._tier
        if tier is None or self._restore_page is None:
            return None
        got = tier.get(child_key, weights_version=weights_version)
        if got is None:
            return None
        rec, _meta, arrays = got
        page = self._restore_page(arrays)
        if page is None:
            return None  # arena full right now; stays spilled
        tier.pop(child_key, restored=True)
        e = self._add(child_key, parent, page, rec.tokens,
                      rec.valid_len)
        # _add holds the cache reference; drop the restore claim
        self.pool.release([page])
        self.update_gauges()
        return e

    # --------------------------------------------------------- matching
    def match(self, tokens, prompt_len, weights_version):
        """Walk the chain for ``tokens[:prompt_len]``. Full pages match
        by exact chain key; a partial tail matches when one cached
        child covers the WHOLE remaining prompt within its
        prefill-valid span. Touches matched entries for LRU. Does NOT
        count hit/miss — the engine records the per-request outcome
        once it knows whether the match was usable."""
        ps = self.page_size
        prompt_len = int(prompt_len)
        key = self.root_key(weights_version)
        entries = []
        k = 0
        tick = next(self._tick)
        while (k + 1) * ps <= prompt_len:
            child_key = (
                key, tuple(int(t) for t in tokens[k * ps:(k + 1) * ps])
            )
            child = self._entries.get(child_key)
            if child is None and self._tier is not None:
                # the resident chain breaks here — a spilled copy of
                # exactly this page restores and the walk continues
                child = self._restore(child_key, key, weights_version)
            if child is None or not child.full:
                break
            child.last_hit = tick
            entries.append(child)
            key = child.key
            k += 1
        tail = None
        r = prompt_len - k * ps
        if 0 < r < ps:
            rest = tuple(int(t) for t in tokens[k * ps:prompt_len])
            for ck in self._children.get(key, ()):
                e = self._entries.get(ck)
                if e is None or e.valid_len < r:
                    continue
                if e.tokens[:r] == rest:
                    e.last_hit = tick
                    tail = e
                    break
            if tail is None and self._tier is not None:
                for ck in self._tier.children(key):
                    rec = self._tier.peek(ck)
                    if rec is None or rec.valid_len < r \
                            or rec.tokens[:r] != rest:
                        continue
                    e = self._restore(ck, key, weights_version)
                    if e is not None:
                        e.last_hit = tick
                        tail = e
                        break
        covered = k * ps + (r if tail is not None else 0)
        return PrefixMatch(entries, tail, covered)

    # -------------------------------------------------------- publishing
    def _add(self, key, parent, page, tokens, valid_len):
        e = PrefixEntry(key, parent, page, tokens, valid_len,
                        next(self._tick))
        self.pool.incref([page])
        self._entries[key] = e
        self._children.setdefault(parent, set()).add(key)
        if self._tier is not None and self._tier.peek(key) is not None:
            # a fresh publish supersedes any spilled copy of this key
            # (e.g. a restore that once failed for arena room): drop
            # it so a later match can never prefer stale tier bytes
            self._tier.pop(key)
        return e

    def publish(self, tokens, prompt_len, page_ids, weights_version):
        """Publish every FULL page of ``tokens[:prompt_len]`` whose
        chain position is not already cached, using the request's own
        ``page_ids`` (chain order). The cache takes one reference per
        newly published page; existing entries win (the earlier
        publisher's page stays shared). Returns the number published."""
        ps = self.page_size
        prompt_len = int(prompt_len)
        key = self.root_key(weights_version)
        published = 0
        k = 0
        while (k + 1) * ps <= prompt_len and k < len(page_ids):
            toks = tuple(int(t) for t in tokens[k * ps:(k + 1) * ps])
            child_key = (key, toks)
            child = self._entries.get(child_key)
            if child is None:
                child = self._add(child_key, key, page_ids[k], toks, ps)
                published += 1
            key = child.key
            k += 1
        if published:
            self.update_gauges()
        return published

    def publish_partial(self, tokens, prompt_len, page_id,
                        weights_version):
        """Publish the partial prompt-tail page (prefill-valid content
        only — ``prompt_len % page_size`` leading slots). Called at
        request FINISH, when the owner can no longer write the page, so
        later same-prefix requests can COW-adopt the whole prompt.
        Dedups by content; a longer or full entry always wins."""
        ps = self.page_size
        prompt_len = int(prompt_len)
        r = prompt_len % ps
        if r == 0:
            return False
        k = prompt_len // ps
        key = self.root_key(weights_version)
        for i in range(k):
            toks = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            child = self._entries.get((key, toks))
            if child is None or not child.full:
                return False  # chain below is not cached; tail useless
            key = child.key
        rest = tuple(int(t) for t in tokens[k * ps:prompt_len])
        for ck in self._children.get(key, ()):
            e = self._entries.get(ck)
            if e is not None and e.valid_len >= r \
                    and e.tokens[:r] == rest:
                return False  # an equal-or-better tail already cached
        self._add((key, rest), key, page_id, rest, r)
        self.update_gauges()
        return True

    def peek(self, key):
        """Resident entry for one chain key, or None — a pure
        bookkeeping lookup: no LRU touch, no tier restore. The
        capacity sweep in ``tools/serve_bench.py --multi-turn`` walks
        chains with this to ask "still servable?" without changing
        what is."""
        return self._entries.get(key)

    # ---------------------------------------------------------- eviction
    def _reclaimable(self, exclude=()):
        """Entries whose page only the cache still references AND whose
        cached descendants are all themselves reclaimable (evicting a
        middle page would orphan still-resident children). ``exclude``
        pages are treated as pinned — the admission gate passes the
        pages the request itself is about to adopt, which eviction
        could never actually reclaim. Iterative post-order walk: chains
        run one entry per page of the longest cached prompt, far past
        any comfortable recursion depth."""
        exclude = set(exclude)
        out = {}
        for root in self._entries:
            if root in out:
                continue
            stack = [(root, False)]
            while stack:
                key, ready = stack.pop()
                if key in out:
                    continue
                kids = [ck for ck in self._children.get(key, ())
                        if ck in self._entries]
                if not ready:
                    stack.append((key, True))
                    stack.extend((ck, False) for ck in kids
                                 if ck not in out)
                    continue
                e = self._entries[key]
                out[key] = (
                    self.pool.refcount(e.page) == 1
                    and e.page not in exclude
                    and all(out.get(ck, False) for ck in kids)
                )
        return out

    def evictable_pages(self, exclude=()):
        """How many cached pages an eviction pass could reclaim right
        now — the engine folds this into its admission feasibility
        check (free + evictable is the true claimable capacity).
        ``exclude``: pages the caller intends to ADOPT, which must not
        count as reclaimable headroom."""
        return sum(
            1 for v in self._reclaimable(exclude).values() if v
        )

    def _drop(self, entry):
        self._entries.pop(entry.key, None)
        kids = self._children.get(entry.parent)
        if kids is not None:
            kids.discard(entry.key)
            if not kids:
                self._children.pop(entry.parent, None)
        self._children.pop(entry.key, None)
        self.pool.release([entry.page])

    def evict(self, n_pages):
        """Reclaim up to ``n_pages`` cold pages, leaf-first in LRU
        order. Only refcount-1 pages are touched — a page some request
        still decodes over is never pulled out from under it. Returns
        the number of pages actually freed.

        Reclaimability is computed ONCE per pass (dropping a leaf can
        only turn its parent into a new leaf, never change any entry's
        verdict — a parent's verdict already required its whole subtree
        reclaimable), then victims pop off a last-hit heap with parents
        pushed as their cached-child count hits zero: O((n + k) log n)
        per pass instead of a full leaf rescan per freed page."""
        ok = self._reclaimable()
        child_count = {
            key: sum(1 for ck in self._children.get(key, ())
                     if ck in self._entries)
            for key, good in ok.items() if good
        }
        # unique tiebreaker: matched siblings share one LRU tick, and
        # the nested-tuple keys do not order (str vs int)
        tie = itertools.count()
        heap = [
            (self._entries[key].last_hit, next(tie), key)
            for key, n in child_count.items() if n == 0
        ]
        heapq.heapify(heap)
        freed = 0
        while freed < n_pages and heap:
            _, _, key = heapq.heappop(heap)
            victim = self._entries.get(key)
            if victim is None:
                continue
            parent = victim.parent
            if self._tier is not None and self._read_page is not None:
                # spill replaces outright eviction: the victim's arena
                # bytes land in the tier (same leaf-first LRU order)
                # before the HBM page frees. Best-effort — a budget
                # refusal or read failure degrades to plain eviction,
                # never an error into the admission path.
                try:
                    self._tier.put(
                        victim.key, victim.parent, victim.tokens,
                        victim.valid_len,
                        self._read_page(victim.page),
                        weights_version=self._current_version(),
                    )
                except Exception:
                    pass
            self._drop(victim)
            freed += 1
            self.evictions.inc()
            if parent in child_count:
                child_count[parent] -= 1
                if child_count[parent] == 0:
                    heapq.heappush(
                        heap,
                        (self._entries[parent].last_hit, next(tie),
                         parent),
                    )
        if freed:
            self.update_gauges()
        return freed

    def flush(self, reason="flush"):
        """Drop EVERY entry and release the cache's page references —
        the weight-swap seam (post-reload requests must never adopt
        pages computed under old weights) and part of engine close."""
        n = len(self._entries)
        for e in list(self._entries.values()):
            self.pool.release([e.page])
        self._entries.clear()
        self._children.clear()
        if self._tier is not None:
            # spilled payloads die with the resident entries: after a
            # weight swap they could never pass the stale check, and
            # keeping them would only squat on the spill budget
            self._tier.flush(reason=reason)
        if n:
            self.flushes += 1
        self.update_gauges()
        return n

    # -------------------------------------------------------- accounting
    @property
    def cached_pages(self):
        return len(self._entries)

    def hbm_saved_bytes(self):
        """Bytes the sharing saves RIGHT NOW: each reference beyond
        (cache + first holder) on a cached page is a private page copy
        a cacheless engine would be holding instead. Only cached pages
        ever carry more than one reference, so the pool's incremental
        over-2 counter IS this quantity — O(1), called per admission
        and per finish on the driver thread."""
        return self.pool.shared_saved_pages * self.pool.page_bytes()

    def update_gauges(self):
        self.hbm_saved.set(float(self.hbm_saved_bytes()))

    def stats(self):
        # scrape-path snapshot: every field is O(1) — the reclaimable
        # walk (evictable_pages) stays in the admission path that
        # actually needs it, not in every router /healthz poll
        return {
            "entries": len(self._entries),
            "cached_pages": self.cached_pages,
            "hits": int(self.hits.value),
            "misses": int(self.misses.value),
            "evictions": int(self.evictions.value),
            "cow_clones": int(self.cow_clones.value),
            "tokens_saved": int(self.tokens_saved.value),
            "hbm_saved_bytes": int(self.hbm_saved_bytes()),
            "flushes": self.flushes,
            **({"tier": self._tier.stats()}
               if self._tier is not None else {}),
        }
