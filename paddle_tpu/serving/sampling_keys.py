"""Per-request PRNG key derivation — ONE scheme for every engine.

Before this module each engine advanced a private ``jax.random.split``
chain per program launch, so a sampled token's randomness depended on
the global interleaving of prefills and decode steps — reproducible
only replay-for-replay on the SAME engine, and never comparable across
the slab and paged engines (their launch orders differ). Speculative
decoding makes that untenable: rejection sampling consumes a variable
number of uniforms per emitted token, and the pinned guarantee (the
output distribution equals vanilla sampling) is only testable when the
randomness is addressable by WHAT is being sampled, not by when.

The scheme (pure ``fold_in`` tree, no mutable chain):

- ``request key`` = ``fold_in(PRNGKey(seed), admission_index)`` — the
  engine-local admission counter, NOT the process-global request id
  (two engines fed the same workload in the same order derive the same
  request keys; the global id would desynchronize them).
- ``position key`` = ``fold_in(request_key, j)`` where ``j`` is the
  cache position the sampled token will occupy. Prefill samples the
  token at ``j = prompt_len``; a chunked prefill at offset ``pos``
  samples ``j = pos + tail_len`` — the SAME position, which is what
  keeps the warm (chunked) path bitwise-equal to the cold path. Decode
  at position ``pos`` samples ``j = pos + 1``. Program bodies do the
  position fold INSIDE the jit (vector ``pos`` folds per row via vmap).
- speculative purposes fold one more constant below the position key:
  draft proposal / acceptance uniform / residual resample each draw
  from a disjoint stream, so speculation never consumes (or collides
  with) the vanilla stream's randomness at any position.

Determinism pin (tier-1): the slab and paged engines produce
IDENTICAL sampled streams for the same seed and submission order.
"""
from __future__ import annotations

import jax

# speculative purpose folds (any distinct constants; folded below the
# position key so the undecorated position key IS the vanilla stream)
DRAFT = 0x5D
ACCEPT = 0x5E
RESIDUAL = 0x5F


class SamplingKeySource:
    """Derives one base key per admitted request off a master seed.

    The counter is the engine-local ADMISSION index: it advances once
    per ``_admit_one``, in admission order — the same order on every
    engine geometry for a fixed workload (the scheduler is strict
    priority-FIFO), which is what makes sampled streams comparable
    across backends."""

    def __init__(self, seed):
        self._master = jax.random.PRNGKey(int(seed))
        self.next_index = 0

    def next_request_key(self):
        key = jax.random.fold_in(self._master, self.next_index)
        self.next_index += 1
        return key


def position_key(request_key, position):
    """The key that samples the token landing at cache ``position`` —
    host-side mirror of the fold the program bodies apply."""
    return jax.random.fold_in(request_key, int(position))


def purpose_key(request_key, position, purpose):
    """A speculative sub-stream (DRAFT / ACCEPT / RESIDUAL) at one
    position: disjoint from the vanilla stream by construction."""
    return jax.random.fold_in(position_key(request_key, position),
                              int(purpose))
