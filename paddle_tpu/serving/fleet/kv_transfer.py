"""Cross-process prefill->decode disaggregation: the KV transfer wire.

The within-process disaggregation lever (``max_prefills_per_step``)
bounds how much prefill work can delay decode, but one host still pays
for both phases. This module splits them across PROCESSES: a
:class:`PrefillWorker` owns the prompt phase — it runs the same
compiled per-bucket prefill program the engines use
(:func:`~..engine.build_prefill_body`) and ships the finished KV block
to the decode replica as PAGE payloads; the decode engine adopts them
through its existing per-bucket adopt-pages scatter and the request
enters the decode batch exactly as if it had prefilled locally.

Wire format (one socket, length-prefixed frames, CRC-checked)::

    frame := MAGIC(4) | payload_len(u64 BE) | crc32(u32 BE) | payload
    payload := header_len(u32 BE) | header_json | raw_bytes

A prefill exchange is one request frame (prompt ids + bucket geometry +
sampling temperature/key) answered by one ``prefilled`` meta frame and
then one frame per cache array, each reshaped to ``[n_pages,
page_size, kvH, D]`` — pages are the transfer unit, mirroring the page
arena they land in. int8 pools ship TWO frames per array (int8 codes +
fp32 scales), so quantized transfer is bit-exact too. A corrupted
frame (bad magic, short read, CRC mismatch) raises
:class:`TransferError`; the engine's response is always the same: fall
back to LOCAL prefill and keep serving (disaggregation is an
optimization, never a correctness dependency).

Exactness contract: worker and engine trace the SAME prefill body over
the SAME weights, so the shipped block and first token are
bit-identical to what local prefill would have produced — the tier-1
test pins arena equality after adoption, and the fleet smoke pins
token streams.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib

import numpy as np

import jax
import jax.numpy as jnp

from ...models.generation import alloc_kv_caches, normalize_cache_dtype
from ...observability.tracing import (
    format_traceparent,
    get_tracer,
    parse_traceparent,
    remote_child_span,
)
from ...quantization.kv import QuantizedKV, is_quantized
from ..chaos import poke as _chaos_poke
from ..engine import _flatten, build_prefill_body
from ..metrics import Counter

# Wire protocol version. PKV2 added the optional trace fields
# (``traceparent`` on the prefill request, ``span`` on the prefilled
# response) — both are carried in the header JSON, so the frame layout
# itself is unchanged and a PKV1 peer's frames still parse: we SEND the
# current magic but ACCEPT both on receive.
MAGIC = b"PKV2"
MAGIC_V1 = b"PKV1"
_ACCEPTED_MAGICS = (MAGIC, MAGIC_V1)
_HEAD = struct.Struct(">QI")   # payload_len, crc32
_HLEN = struct.Struct(">I")    # header_json length
# one frame is at most a few pages of KV; anything past this is a
# corrupted length field, not a real payload
MAX_FRAME_BYTES = 1 << 31


class TransferError(RuntimeError):
    """Any failure of the KV transfer path (connect, frame, CRC,
    worker-side error). The decode engine catches exactly this and
    falls back to local prefill."""


# ------------------------------------------------------------------ frames
def send_frame(sock, header, blob=b""):
    # chaos seam: a fault armed here IS a socket drop mid-exchange
    _chaos_poke("kv.send_frame", kind=header.get("kind")
                or header.get("part"))
    hj = json.dumps(header).encode("utf-8")
    payload = _HLEN.pack(len(hj)) + hj + bytes(blob)
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    try:
        sock.sendall(MAGIC + _HEAD.pack(len(payload), crc) + payload)
    except OSError as e:
        raise TransferError(f"send failed: {e!r}")


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(min(1 << 20, n - len(buf)))
        except OSError as e:
            raise TransferError(f"recv failed: {e!r}")
        if not chunk:
            raise TransferError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock):
    _chaos_poke("kv.recv_frame")
    head = _recv_exact(sock, 4 + _HEAD.size)
    if head[:4] not in _ACCEPTED_MAGICS:
        raise TransferError(f"bad frame magic {head[:4]!r}")
    length, crc = _HEAD.unpack(head[4:])
    if length < _HLEN.size or length > MAX_FRAME_BYTES:
        raise TransferError(f"implausible frame length {length}")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TransferError("frame CRC mismatch")
    hlen = _HLEN.unpack(payload[:_HLEN.size])[0]
    if _HLEN.size + hlen > length:
        raise TransferError("frame header overruns payload")
    try:
        header = json.loads(payload[_HLEN.size:_HLEN.size + hlen]
                            .decode("utf-8"))
    except Exception as e:
        raise TransferError(f"bad frame header: {e!r}")
    return header, payload[_HLEN.size + hlen:]


def _encode_array(arr):
    a = np.asarray(arr)
    return {"dtype": str(a.dtype), "shape": list(a.shape)}, a.tobytes()


def _decode_array(header, blob):
    dt = jnp.dtype(header["dtype"])
    n = int(np.prod(header["shape"])) * dt.itemsize
    if len(blob) != n:
        raise TransferError(
            f"array payload {len(blob)}B != expected {n}B for "
            f"{header['dtype']}{header['shape']}"
        )
    return np.frombuffer(blob, dtype=dt).reshape(header["shape"])


# ------------------------------------------------------------------ worker
class PrefillWorker:
    """The prefill pool's unit: a socket server that runs bucketed
    prefill and ships the finished KV pages.

    Holds a weights snapshot of ``net`` (same discipline as the
    engines) and compiles one prefill program per ``(bucket,
    cache_dtype)`` on demand — the block arrays are reused across
    requests exactly like the engines' bucketed block pool (every
    bucket position is rewritten each prefill). Requests are served
    one at a time under a lock: prefill is compute-bound, and the
    decode replicas' fallback path means a slow worker degrades to
    local prefill rather than queueing.

    ``do_sample``/``top_k``/``top_p`` are baked into the compiled
    program and must match the decode engines'; temperature and the
    PRNG key travel per request, so sampled streams stay reproducible.
    """

    def __init__(self, net, *, host="127.0.0.1", port=0, do_sample=False,
                 top_k=0, top_p=1.0, weights_version=None):
        self.net = net
        self.config = net.config
        self.do_sample = bool(do_sample)
        self.top_k = int(top_k)
        self.top_p = float(top_p) if top_p is not None else 1.0
        self.weights_version = (
            "v0" if weights_version is None else str(weights_version)
        )
        self._params = {k: p.value for k, p in net.named_parameters()}
        self._buffers = {k: b.value for k, b in net.named_buffers()}
        self._was_training = net.training
        self._fns = {}      # (bucket, dtype_name) -> jitted program
        self._blocks = {}   # (bucket, dtype_name) -> flat block arrays
        self._traced = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.host = host
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = None
        from ...analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)
        self.served = 0
        self.errors = 0

    # ---------------------------------------------------------- lifecycle
    def start(self):
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="paddle-prefill-worker",
            daemon=True,
        )
        self._accept_thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def stats(self):
        return {
            "port": self.port,
            "served": self.served,
            "errors": self.errors,
            "weights_version": self.weights_version,
            "buckets": sorted({b for b, _ in self._fns}),
        }

    # ------------------------------------------------------------- serving
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
            ).start()

    def _serve_conn(self, conn):
        conn.settimeout(60.0)
        try:
            while not self._stop.is_set():
                try:
                    req, _ = recv_frame(conn)
                except TransferError:
                    return  # client went away / corrupt stream
                try:
                    if req.get("kind") == "ping":
                        send_frame(conn, {"kind": "pong",
                                          "stats": self.stats()})
                        continue
                    if req.get("kind") == "reload":
                        res = self.reload_weights(
                            req["ckpt_dir"],
                            weights_version=req.get("weights_version"),
                        )
                        send_frame(conn, {"kind": "reloaded", **res})
                        continue
                    if req.get("kind") != "prefill":
                        raise ValueError(
                            f"unknown request kind {req.get('kind')!r}"
                        )
                    self._handle_prefill(conn, req)
                    with self._lock:
                        # per-connection threads all bump these; an
                        # unlocked += tears under contention
                        self.served += 1
                except TransferError:
                    with self._lock:
                        self.errors += 1
                    return  # send path broken; nothing else to say
                except Exception as e:
                    with self._lock:
                        self.errors += 1
                    try:
                        send_frame(conn, {"kind": "error",
                                          "error": repr(e)})
                    except TransferError:
                        return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def reload_weights(self, ckpt_dir, weights_version=None):
        """Rotate the PREFILL side onto a new committed checkpoint —
        same verify/load/validate path as the engines' live reload, so
        worker and replicas can be walked through one rotation and the
        version-skew refusal closes the window in between. The swap
        happens under the serving lock (never mid-prefill). Returns
        the reload result as a plain dict (it travels over the wire as
        the ``reloaded`` frame)."""
        from ..reload import prepare_state_swap

        staged = prepare_state_swap(
            self.net, self._params, self._buffers, ckpt_dir,
            weights_version=weights_version,
        )
        if staged.ok:
            with self._lock:
                self._params = staged.params
                self._buffers = staged.buffers
                self.weights_version = staged.weights_version
                staged.outcome = "applied"
        return staged.to_json()

    def _program(self, bucket, dtype_name):
        key = (bucket, dtype_name)
        fn = self._fns.get(key)
        if fn is None:
            body = build_prefill_body(self.net, self.do_sample,
                                      self.top_k, self.top_p)
            fn = jax.jit(body)
            self._fns[key] = fn
        blk = self._blocks.get(key)
        if blk is None:
            blk = _flatten(alloc_kv_caches(self.config, 1, bucket,
                                           dtype_name))
            self._blocks[key] = blk
        return fn, blk

    def _handle_prefill(self, conn, req):
        bucket = int(req["bucket"])
        ps = int(req["page_size"])
        prompt = [int(t) for t in req["prompt"]]
        L = int(req["prompt_len"])
        if L != len(prompt) or not 1 <= L <= bucket:
            raise ValueError(
                f"prompt_len {L} inconsistent with prompt/bucket "
                f"{len(prompt)}/{bucket}"
            )
        if ps < 1 or bucket % ps:
            raise ValueError(
                f"page_size {ps} must divide bucket {bucket}"
            )
        dtype_name = normalize_cache_dtype(req["cache_dtype"])
        # PKV2 trace propagation: a sampled client sends a traceparent;
        # we time the compute under a tracer-less span and ship it back
        # in the response header — the CLIENT adds it to its buffer, so
        # the worker needs no trace endpoint of its own (and an
        # in-process worker never double-records).
        wsp = None
        ctx = parse_traceparent(req.get("traceparent"))
        if ctx is not None:
            wsp = remote_child_span("worker.prefill", ctx,
                                    "prefill_worker")
            wsp.set(bucket=bucket, prompt_len=L)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = prompt
        key = jnp.asarray(np.asarray(req["key"], np.uint32))
        with self._lock:
            fn, blk = self._program(bucket, dtype_name)
            nxt, new_flat = fn(
                self._params, self._buffers, jnp.asarray(ids),
                jnp.int32(L), blk,
                jnp.float32(req.get("temperature", 1.0)), key,
            )
            trace_key = ("prefill", bucket, dtype_name)
            if trace_key not in self._traced:
                # tracing swapped tracers into the Layer objects —
                # restore concrete state (the engines' _run discipline)
                self._traced.add(trace_key)
                self.net.load_functional_state(self._params,
                                               self._buffers)
                if self._was_training:
                    self.net.train()
                else:
                    self.net.eval()
            # the returned block (this request's KV) doubles as the
            # next request's input block, like the engines' block pool
            self._blocks[(bucket, dtype_name)] = new_flat
            t0 = int(np.asarray(nxt)[0])
        n_pages = bucket // ps
        meta = {
            "kind": "prefilled", "first_token": t0, "bucket": bucket,
            "page_size": ps, "n_pages": n_pages,
            "cache_dtype": dtype_name, "entries": len(new_flat),
            "weights_version": self.weights_version,
        }
        if wsp is not None:
            wsp.finish(weights_version=self.weights_version)
            meta["span"] = wsp.to_dict()
        send_frame(conn, meta)
        for arr in new_flat:
            if is_quantized(arr):
                kvh, d = arr.q.shape[2], arr.q.shape[3]
                h, b = _encode_array(
                    np.asarray(arr.q)[0].reshape(n_pages, ps, kvh, d)
                )
                send_frame(conn, dict(h, part="q"), b)
                h, b = _encode_array(
                    np.asarray(arr.scale)[0].reshape(n_pages, ps, kvh)
                )
                send_frame(conn, dict(h, part="scale"), b)
            else:
                a = np.asarray(arr)
                kvh, d = a.shape[2], a.shape[3]
                h, b = _encode_array(a[0].reshape(n_pages, ps, kvh, d))
                send_frame(conn, dict(h, part="dense"), b)


# ------------------------------------------------------------------ client
class RemotePrefillClient:
    """The decode replica's end of the transfer: attached to a
    ``PagedServingEngine`` as ``prefill_transport``, it ships each
    admission's prompt to the prefill pool and returns ``(first_token,
    flat_block)`` ready for the engine's adopt-pages program.

    Single-threaded by design (only the engine's driver thread calls
    it). Any failure raises :class:`TransferError` AND opens a
    cooldown window — ``available()`` goes False for ``cooldown_s`` so
    a dead worker costs one connect timeout, not one per admission —
    then half-opens for a fresh attempt."""

    def __init__(self, host, port, *, timeout_s=10.0, cooldown_s=2.0,
                 expected_weights_version=None, registry=None,
                 clock=time.monotonic):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.cooldown_s = float(cooldown_s)
        self.expected_weights_version = expected_weights_version
        self.clock = clock
        self._sock = None
        self._down_until = 0.0
        self.transfers = Counter(
            "kv_transfers", labelname="outcome",
            prom_name="paddle_fleet_kv_transfers_total",
            help="remote prefill transfers, by outcome")
        self.transfer_bytes = Counter(
            "kv_transfer_bytes",
            prom_name="paddle_fleet_kv_transfer_bytes_total",
            help="KV page payload bytes received from the prefill pool")
        if registry is None:
            from ...observability import get_registry

            registry = get_registry()
        registry.register_all([self.transfers, self.transfer_bytes])

    def available(self):
        return self.clock() >= self._down_until

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _mark_down(self):
        self.close()
        self._down_until = self.clock() + self.cooldown_s

    def _connection(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s
            )
        return self._sock

    def prefill(self, prompt, prompt_len, bucket, page_size,
                cache_dtype, temperature, key, trace=None):
        """One remote prefill: returns ``(first_token, flat_block)``
        where ``flat_block`` matches the engine's local prefill output
        (``[1, bucket, kvH, D]`` per K/V per layer; ``QuantizedKV``
        for int8 pools). Raises :class:`TransferError` on ANY failure
        after opening the cooldown window.

        ``trace`` (a Span or None) makes the exchange traced: a
        ``kv.transfer`` wire span brackets the socket round-trip, its
        traceparent rides the PKV2 request header, and the worker's
        returned ``worker.prefill`` span lands in THIS process's trace
        buffer (the worker keeps no buffer of its own).

        A failure on a REUSED connection gets one fresh-connection
        retry first: the worker idle-closes connections (and may have
        restarted), and a stale cached socket must not demote a
        healthy worker to local-prefill + cooldown. Prefill is pure
        compute, so the retry is safe to replay."""
        tr = get_tracer()
        wire = None if trace is None else tr.start_span(
            "kv.transfer", trace,
            worker=f"{self.host}:{self.port}", bucket=int(bucket),
        )
        tid = None if wire is None else wire.trace_id
        args = (prompt, prompt_len, bucket, page_size, cache_dtype,
                temperature, key, wire)
        reused = self._sock is not None
        try:
            t0, flat, nbytes, wspan = self._prefill_once(*args)
        except TransferError as e:
            retried = False
            if reused:
                self.close()
                retried = True
                try:
                    t0, flat, nbytes, wspan = self._prefill_once(*args)
                except TransferError as e2:
                    e, retried = e2, False
            if not retried:
                self._mark_down()
                self.transfers.inc(label="error", trace_id=tid)
                if wire is not None:
                    wire.finish(outcome="error", error=str(e))
                raise e
        self.transfers.inc(label="ok", trace_id=tid)
        self.transfer_bytes.inc(nbytes, trace_id=tid)
        if wire is not None:
            wire.finish(outcome="ok", bytes=nbytes)
            if wspan:
                tr.buffer.add(wspan)
        return t0, flat

    def _prefill_once(self, prompt, prompt_len, bucket, page_size,
                      cache_dtype, temperature, key, wire=None):
        try:
            sock = self._connection()
            req = {
                "kind": "prefill",
                "prompt": [int(t) for t in prompt],
                "prompt_len": int(prompt_len),
                "bucket": int(bucket),
                "page_size": int(page_size),
                "cache_dtype": str(cache_dtype),
                "temperature": float(temperature),
                "key": [int(x) for x in np.asarray(key).ravel()],
            }
            if wire is not None:
                req["traceparent"] = format_traceparent(wire)
            send_frame(sock, req)
            meta, _ = recv_frame(sock)
            if meta.get("kind") == "error":
                raise TransferError(
                    f"worker error: {meta.get('error')}"
                )
            if meta.get("kind") != "prefilled":
                raise TransferError(
                    f"unexpected response kind {meta.get('kind')!r}"
                )
            if (self.expected_weights_version is not None
                    and meta.get("weights_version")
                    != self.expected_weights_version):
                raise TransferError(
                    f"weights version skew: worker serves "
                    f"{meta.get('weights_version')!r}, engine expects "
                    f"{self.expected_weights_version!r}"
                )
            bkt = int(meta["bucket"])
            flat, nbytes = [], 0
            for _ in range(int(meta["entries"])):
                h, blob = recv_frame(sock)
                nbytes += len(blob)
                if h.get("part") == "q":
                    hs, sb = recv_frame(sock)
                    nbytes += len(sb)
                    if hs.get("part") != "scale":
                        raise TransferError(
                            "quantized entry missing its scale frame"
                        )
                    q = _decode_array(h, blob)
                    s = _decode_array(hs, sb)
                    kvh, d = q.shape[2], q.shape[3]
                    flat.append(QuantizedKV(
                        jnp.asarray(q.reshape(1, bkt, kvh, d)),
                        jnp.asarray(s.reshape(1, bkt, kvh)),
                    ))
                else:
                    a = _decode_array(h, blob)
                    kvh, d = a.shape[2], a.shape[3]
                    flat.append(
                        jnp.asarray(a.reshape(1, bkt, kvh, d))
                    )
        except TransferError:
            self.close()  # protocol state unknown; never reuse it
            raise
        except (OSError, KeyError, ValueError) as e:
            self.close()
            raise TransferError(repr(e))
        return (int(meta["first_token"]), flat, nbytes,
                meta.get("span"))

    def reload(self, ckpt_dir, weights_version=None,
               reload_timeout_s=120.0):
        """Ask the worker to rotate onto a committed checkpoint.
        Returns the worker's reload-result dict; on success with a
        version-pinned client, ``expected_weights_version`` follows the
        worker so subsequent transfers match again. Raises
        :class:`TransferError` on transport failure.

        The reply only arrives after the worker has CRC-verified and
        loaded the whole checkpoint synchronously, so the exchange runs
        under its own ``reload_timeout_s`` budget (the prefill-sized
        ``timeout_s`` would time a healthy rotation out and report a
        swap that actually landed as failed — the router's HTTP reload
        path uses its stream budget for the same reason). Like
        :meth:`prefill`, a failure on a REUSED connection gets one
        fresh-connection retry: the worker idle-closes sockets, and a
        stale cached one must not report a rotation as failed (the
        exchange is replay-safe — prepare is pure, apply idempotent)."""
        reused = self._sock is not None
        try:
            meta = self._reload_once(ckpt_dir, weights_version,
                                     reload_timeout_s)
        except TransferError:
            if not reused:
                self._mark_down()
                raise
            self.close()
            try:
                meta = self._reload_once(ckpt_dir, weights_version,
                                         reload_timeout_s)
            except TransferError:
                self._mark_down()
                raise
        if meta.get("ok") and \
                self.expected_weights_version is not None:
            self.expected_weights_version = meta.get("weights_version")
        return meta

    def _reload_once(self, ckpt_dir, weights_version, reload_timeout_s):
        try:
            sock = self._connection()
            sock.settimeout(float(reload_timeout_s))
            try:
                send_frame(sock, {
                    "kind": "reload", "ckpt_dir": str(ckpt_dir),
                    "weights_version": weights_version,
                })
                meta, _ = recv_frame(sock)
            finally:
                try:
                    sock.settimeout(self.timeout_s)
                except OSError:
                    pass
            if meta.get("kind") != "reloaded":
                raise TransferError(
                    f"unexpected reload response {meta.get('kind')!r}"
                )
        except TransferError:
            self.close()  # protocol state unknown; never reuse it
            raise
        except OSError as e:
            self.close()
            raise TransferError(repr(e))
        return meta

    def ping(self):
        """Round-trip liveness probe; returns the worker's stats dict
        or raises :class:`TransferError`."""
        try:
            sock = self._connection()
            send_frame(sock, {"kind": "ping"})
            meta, _ = recv_frame(sock)
            if meta.get("kind") != "pong":
                raise TransferError(
                    f"unexpected ping response {meta.get('kind')!r}"
                )
            return meta.get("stats", {})
        except (OSError, TransferError) as e:
            self._mark_down()
            raise TransferError(repr(e))
