"""Subprocess entrypoints + spawn helpers for fleet components.

``python -m paddle_tpu.serving.fleet.launch --role replica`` puts a
``PagedServingEngine`` behind a ``ServingFrontend`` on an ephemeral
port; ``--role prefill`` starts a :class:`~.kv_transfer.PrefillWorker`.
Either prints exactly one line::

    FLEET_READY role=<role> port=<port>

to stdout once it is serving, then runs until SIGTERM/SIGINT (replicas
stop the frontend and close the engine on the way out). The model is
built from ``paddle.seed(--seed)`` + the tiny-llama knobs, so every
process launched with the same arguments serves IDENTICAL weights —
which is what makes router fail-over and disaggregated prefill
token-exact across processes.

:func:`spawn` is the parent-side helper ``serve_bench --fleet``,
``make fleet-smoke`` and the tests share: launch, wait for the READY
line, keep draining the child's output into a bounded tail ring (so a
chatty child can never block on a full pipe), and hand back the port.
"""
from __future__ import annotations

import argparse
import collections
import os
import signal
import subprocess
import sys
import threading
import time


def build_net(args):
    import paddle_tpu as paddle
    from ...models import LlamaConfig, LlamaForCausalLM

    paddle.seed(args.seed)
    cfg = LlamaConfig.tiny(
        vocab_size=args.vocab, hidden_size=args.hidden,
        intermediate_size=2 * args.hidden,
        num_hidden_layers=args.layers,
        num_attention_heads=args.heads,
    )
    net = LlamaForCausalLM(cfg)
    net.eval()
    return net


def _warmup(engine, args):
    """Compile (or AOT-load) every fixed-shape program before the
    READY line, so the first real requests pay sockets, not XLA.

    ``engine.warmup`` builds the decode step plus prefill AND adopt
    per prompt bucket directly — the local-fallback prefill programs
    are warm even when a prefill transport is attached, so a worker
    outage never stalls decode behind a compile. With ``--aot-cache``
    the finished executables persist, and a relaunched replica loads
    them instead of compiling: READY with zero traces, zero new
    trace-guard entries at first traffic. One real request then runs
    end-to-end (transport detached — warmup traffic must not consume
    the prefill pool) as the serve-path sanity pass; with a transport
    attached, one request per bucket additionally runs THROUGH it, so
    the prefill worker's lazily-compiled per-bucket programs are warm
    too — its first real remote prefill must not stall every replica
    behind an XLA compile under the worker's serving lock."""
    import numpy as np

    stats = engine.warmup(aot_cache=args.aot_cache)
    print(f"FLEET_WARMUP programs={stats['programs']} "
          f"aot_hits={stats['aot_hits']} "
          f"aot_saves={stats['aot_saves']}", flush=True)
    transport = engine.prefill_transport
    engine.prefill_transport = None
    try:
        L = min(args.min_bucket, args.max_seq - 2)
        h = engine.submit(np.zeros((1, L), np.int32), 2)
        engine.run_until_idle()
        assert h.status == "DONE", (
            f"warmup request ended {h.status} ({h.reason})"
        )
    finally:
        engine.prefill_transport = transport
    if transport is not None:
        bucket = engine.pool.bucket_for(min(args.min_bucket,
                                            args.max_seq - 2))
        while bucket <= args.max_seq:
            L = min(bucket, args.max_seq - 2)
            h = engine.submit(np.zeros((1, L), np.int32), 2)
            engine.run_until_idle()
            assert h.status == "DONE", (
                f"remote warmup for bucket {bucket} ended "
                f"{h.status} ({h.reason})"
            )
            if bucket >= args.max_seq:
                break
            bucket *= 2
    engine.metrics = type(engine.metrics)()
    engine.remote_prefills = 0
    engine.local_prefills = 0
    engine.remote_prefill_fallbacks = 0


def main(argv=None):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--role", choices=("replica", "prefill", "router"),
                    default="replica")
    # router role
    ap.add_argument("--replicas", default=None,
                    metavar="HOST:PORT,HOST:PORT",
                    help="router: comma-separated replica frontends")
    ap.add_argument("--watch-ckpt-root", default=None, metavar="DIR",
                    help="router: poll this checkpoint root and run "
                         "the rolling /admin/reload walk whenever a "
                         "NEW manifest-committed step appears — "
                         "publishing a checkpoint needs zero admin "
                         "POSTs")
    ap.add_argument("--watch-interval", type=float, default=1.0,
                    help="router: checkpoint-root poll period, seconds")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    # model (must match across the fleet for exactness)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    # engine geometry
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--max-seq", type=int, default=64)
    ap.add_argument("--min-bucket", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--cache-dtype", default="bfloat16")
    ap.add_argument("--weights-version", default="v0")
    ap.add_argument("--prefill-worker", default=None, metavar="HOST:PORT",
                    help="attach this replica to a prefill pool worker "
                         "(disaggregated prefill with local fallback)")
    ap.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="persistent AOT compile cache: warmup "
                         "serializes compiled programs here; a "
                         "relaunch loads them instead of compiling")
    ap.add_argument("--no-warmup", dest="warmup", action="store_false")
    args = ap.parse_args(argv)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *a: stop.set())

    # name this process's trace rows before any span exists — stitched
    # fleet traces show router/replica/prefill_worker as separate
    # Perfetto process lanes (replicas additionally keyed by port once
    # known, via PADDLE_TPU_TRACE_PROCESS set by the spawner)
    from ...observability.tracing import set_process_name

    set_process_name(os.environ.get("PADDLE_TPU_TRACE_PROCESS")
                     or ("prefill_worker" if args.role == "prefill"
                         else args.role))

    if args.role == "router":
        if not args.replicas:
            ap.error("--role router requires --replicas")
        from .router import FleetRouter

        router = FleetRouter(
            [s.strip() for s in args.replicas.split(",") if s.strip()],
            host=args.host, port=args.port,
            watch_ckpt_root=args.watch_ckpt_root,
            watch_interval_s=args.watch_interval,
        ).start()
        print(f"FLEET_READY role=router port={router.port}",
              flush=True)
        stop.wait()
        router.stop()
        return 0

    net = build_net(args)

    if args.role == "prefill":
        from .kv_transfer import PrefillWorker

        worker = PrefillWorker(
            net, host=args.host, port=args.port,
            weights_version=args.weights_version,
        ).start()
        print(f"FLEET_READY role=prefill port={worker.port}",
              flush=True)
        stop.wait()
        worker.stop()
        return 0

    from ..http_frontend import ServingFrontend
    from ..paged_engine import PagedServingEngine

    transport = None
    if args.prefill_worker:
        from .kv_transfer import RemotePrefillClient

        whost, _, wport = args.prefill_worker.rpartition(":")
        transport = RemotePrefillClient(
            whost or "127.0.0.1", int(wport),
            expected_weights_version=args.weights_version,
        )
    engine = PagedServingEngine(
        net, max_batch_size=args.max_batch, max_seq_len=args.max_seq,
        min_bucket=args.min_bucket, page_size=args.page_size,
        num_pages=args.num_pages, max_queue_size=args.max_queue,
        cache_dtype=args.cache_dtype,
        weights_version=args.weights_version,
        prefill_transport=transport,
    )
    if args.warmup:
        _warmup(engine, args)
    fe = ServingFrontend(engine, host=args.host,
                         port=args.port).start()
    print(f"FLEET_READY role=replica port={fe.port}", flush=True)
    stop.wait()
    fe.stop(close_engine=True)
    return 0


# --------------------------------------------------------------- spawning
class FleetProc:
    """A spawned fleet component: the Popen, its READY port, and a
    bounded tail of its merged stdout/stderr (diagnostics on failure —
    and the drain keeps the child from blocking on a full pipe).
    ``lines`` is the queue the spawn-time reader thread feeds (one
    reader per child; ``None`` marks EOF)."""

    def __init__(self, proc, port, role, lines):
        self.proc = proc
        self.port = port
        self.role = role
        self._lines = lines
        self.tail = collections.deque(maxlen=400)
        self._drainer = threading.Thread(target=self._drain,
                                         daemon=True)
        self._drainer.start()

    def _drain(self):
        while True:
            line = self._lines.get()
            if line is None:
                return
            self.tail.append(line.rstrip("\n"))

    @property
    def alive(self):
        return self.proc.poll() is None

    def terminate(self, timeout_s=15.0):
        """Graceful stop (SIGTERM -> SIGKILL after the timeout)."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(5)
        return self.proc.returncode

    def kill(self):
        """SIGKILL — the fleet smoke's replica-death scenario."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(5)
        return self.proc.returncode


def _popen(role, cli_args, env):
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    child_env = dict(os.environ)
    child_env.update(env or {})
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    child_env["PYTHONUNBUFFERED"] = "1"
    child_env["PYTHONPATH"] = (
        repo_root + os.pathsep + child_env.get("PYTHONPATH", "")
    )
    cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.launch",
           "--role", role, *[str(a) for a in cli_args]]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=child_env, cwd=repo_root,
    )
    # a reader thread owns the pipe: readline() in the caller would
    # block past the deadline on a child that wedges without printing
    import queue as _queue

    lines = _queue.Queue()

    def _reader():
        try:
            for line in proc.stdout:
                lines.put(line)
        except ValueError:
            pass  # pipe closed at shutdown
        lines.put(None)

    threading.Thread(target=_reader, daemon=True).start()
    return proc, lines


def _wait_ready(proc, lines, role, timeout_s):
    import queue as _queue

    head = []
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(
                1.0, max(deadline - time.monotonic(), 0.05)))
        except _queue.Empty:
            continue
        if line is None:
            proc.wait()
            raise RuntimeError(
                f"fleet {role} exited rc={proc.returncode} before "
                f"READY:\n" + "\n".join(head[-40:])
            )
        head.append(line.rstrip("\n"))
        if line.startswith("FLEET_READY"):
            port = int(line.rsplit("port=", 1)[1].strip())
            return FleetProc(proc, port, role, lines)
    proc.kill()
    raise RuntimeError(
        f"fleet {role} not READY within {timeout_s}s:\n"
        + "\n".join(head[-40:])
    )


def spawn(role="replica", cli_args=(), *, timeout_s=300.0, env=None):
    """Launch one fleet component subprocess and wait for its READY
    line. Returns a :class:`FleetProc`. Raises RuntimeError (with the
    child's output) when the child dies or never reports ready."""
    proc, lines = _popen(role, cli_args, env)
    return _wait_ready(proc, lines, role, timeout_s)


def spawn_all(specs, *, timeout_s=300.0, env=None):
    """Launch MANY components concurrently: all Popens start first,
    then each READY line is awaited — the children's XLA warmups run
    in parallel instead of being serialized by the parent. ``specs``
    is a list of ``(role, cli_args)``. On any failure the already-
    spawned children are killed before the error propagates."""
    started = [(role, *_popen(role, args, env)) for role, args in specs]
    procs = []
    try:
        for role, proc, lines in started:
            procs.append(_wait_ready(proc, lines, role, timeout_s))
    except BaseException:
        for _, proc, _ in started:
            if proc.poll() is None:
                proc.kill()
        raise
    return procs


if __name__ == "__main__":
    sys.exit(main())
