"""paddle_tpu.serving.fleet — cluster serving tier.

The unit of scaling above one engine: a *fleet* of engine replicas
behind an occupancy-aware routing front-end, with true cross-process
prefill->decode disaggregation.

- :mod:`router` — :class:`FleetRouter`: a stdlib-HTTP front-end that
  terminates ``/v1/generate`` SSE and places each request on the
  least-loaded healthy replica (free pages x queue depth, scraped from
  the replicas' machine-readable ``/healthz`` status), with per-replica
  circuit breaking, bounded retry of UNSTARTED requests, shed-with-
  reason when the whole fleet is saturated, and an aggregated
  ``/metrics`` exposition carrying per-replica health series.
- :mod:`kv_transfer` — the disaggregation wire: a
  :class:`PrefillWorker` runs bucketed prefill and ships the finished
  KV pages (bf16 or int8 + scales) as length-prefixed, CRC-checked
  page payloads over a socket; a :class:`RemotePrefillClient` attached
  to a ``PagedServingEngine`` adopts them through the existing
  per-bucket adopt-pages programs. Token streams are EXACT-EQUAL to
  local prefill (same compiled program, same weights), and any
  transfer failure falls back to local prefill cleanly.
- :mod:`launch` — subprocess entrypoints (``python -m
  paddle_tpu.serving.fleet.launch``) that put a replica or a prefill
  worker on an ephemeral port, plus the spawn helpers
  ``serve_bench --fleet`` / ``make fleet-smoke`` / tests share.

Everything is stdlib + the existing serving stack: single-machine
multi-process today, and the seam multi-host pools deploy behind.
"""
from .kv_transfer import (  # noqa: F401
    PrefillWorker,
    RemotePrefillClient,
    TransferError,
)
from .router import FleetRouter, RouterMetrics  # noqa: F401
