"""Occupancy-aware replica router — the fleet's front door.

One :class:`~..http_frontend.ServingFrontend` serves one engine; at the
millions-of-users north star the unit of scaling is a FLEET of them.
:class:`FleetRouter` is a stdlib-HTTP front-end with the same wire
surface (``POST /v1/generate`` -> SSE token stream) that places each
request on the best replica:

- **Admission signal**: a scrape loop polls every replica's
  machine-readable ``/healthz`` status (free pages, queue depth,
  in-flight, draining, generation) and publishes it as per-replica
  gauges. Placement picks the eligible replica with the LOWEST load
  score ``(1 + queue_depth + active + routed_in_flight) / (1 +
  free_pages)`` — free pages are capacity, queue depth is pressure,
  and the router's own in-flight count covers scrape staleness.
- **Cache affinity**: replicas running a prefix cache (the paged
  engine's COW page sharing) serve a warm prefix with near-zero
  prefill compute and near-zero marginal HBM — but only on the
  replica that already holds the pages. The router remembers which
  replica last served each prompt-prefix head (first
  ``affinity_prefix_tokens`` input ids, bounded LRU map) and divides
  that replica's load score by ``1 + affinity_bonus``: same-prefix
  traffic converges onto the warm replica until real load outweighs
  the bonus. Recorded at placement time so concurrent same-prefix
  requests converge immediately.
- **Circuit breaking**: request-path failures (connect errors, 5xx)
  count per replica; past ``breaker_threshold`` consecutive failures
  the breaker OPENS for ``breaker_cooldown_s`` (placement skips it),
  then half-opens for one fresh attempt. A success closes it.
- **Bounded retry of UNSTARTED requests**: a request that failed
  before its first token event (connect refused, replica 429/503,
  mid-handshake death) is retried on the next-best replica, each
  eligible replica tried at most once. A request that already
  streamed tokens is NEVER replayed — its stream ends with a terminal
  ``event: error`` carrying the reason (``replica_failed``), because
  re-running a partially-streamed decode would duplicate tokens.
- **Shed with reason**: when every eligible replica rejects with
  backpressure the client gets HTTP 429 ``{"reason":
  "fleet_saturated"}`` BEFORE any stream opens; an empty/unhealthy
  fleet sheds 503 ``no_replicas``; all-connect-failures sheds 502
  ``replicas_unavailable``.
- **Aggregated /metrics**: the router's process registry exposition
  carries the routing counters AND the per-replica health series
  (``paddle_fleet_replica_{healthy,free_pages,queue_depth,active}``),
  so one scrape shows the whole fleet.

Admin surface: ``GET /replicas`` (full status JSON), ``POST
/admin/drain/<i>`` / ``/admin/undrain/<i>`` proxy the replica's drain
toggle and immediately stop/resume routing to it — rotate a replica
out with zero dropped requests.
"""
from __future__ import annotations

import collections
import json
import threading
import time

from ...observability import Gauge, get_registry
from ...observability.exporter import prometheus_text
from ...observability.tracing import (
    TRACEPARENT_HEADER,
    Tracer,
    format_traceparent,
    trace_payload,
)
from ..metrics import Counter, Histogram

# terminal stream-abort reasons the router originates
ABORT_REPLICA_FAILED = "replica_failed"
ABORT_CLIENT_DISCONNECT = "client_disconnect"

SHED_FLEET_SATURATED = "fleet_saturated"
SHED_NO_REPLICAS = "no_replicas"
SHED_REPLICAS_UNAVAILABLE = "replicas_unavailable"

_SHED_STATUS = {
    SHED_FLEET_SATURATED: 429,
    SHED_NO_REPLICAS: 503,
    SHED_REPLICAS_UNAVAILABLE: 502,
}


class RouterMetrics:
    """The router's registry instruments: routing counters + the
    per-replica health gauges the scrape loop feeds."""

    def __init__(self, registry=None, namespace="paddle_fleet"):
        ns = namespace
        self.requests = Counter(
            "fleet_requests", labelname="replica",
            prom_name=f"{ns}_requests_total",
            help="requests routed, by replica index")
        self.http_requests = Counter(
            "fleet_http_requests", labelname="code",
            prom_name=f"{ns}_http_requests_total",
            help="router HTTP responses, by status code")
        self.retries = Counter(
            "fleet_retries", labelname="reason",
            prom_name=f"{ns}_retries_total",
            help="unstarted requests retried on another replica, by "
                 "trigger")
        self.shed = Counter(
            "fleet_shed", labelname="reason",
            prom_name=f"{ns}_shed_total",
            help="requests shed by the router, by reason")
        self.breaker_opens = Counter(
            "fleet_breaker_opens", labelname="replica",
            prom_name=f"{ns}_breaker_opens_total",
            help="circuit-breaker opens, by replica index")
        self.stream_aborts = Counter(
            "fleet_stream_aborts", labelname="reason",
            prom_name=f"{ns}_stream_aborts_total",
            help="router-side streams ended by a terminal error event")
        self.ttft = Histogram(
            "fleet_ttft", prom_name=f"{ns}_router_ttft_seconds",
            help="router-received to first token byte forwarded")
        self.replica_healthy = Gauge(
            "fleet_replica_healthy",
            prom_name=f"{ns}_replica_healthy",
            help="1 when the replica's last status scrape succeeded")
        self.replica_free_pages = Gauge(
            "fleet_replica_free_pages",
            prom_name=f"{ns}_replica_free_pages",
            help="free KV pages from the replica's last status")
        self.replica_queue_depth = Gauge(
            "fleet_replica_queue_depth",
            prom_name=f"{ns}_replica_queue_depth",
            help="scheduler queue depth from the replica's last status")
        self.replica_active = Gauge(
            "fleet_replica_active",
            prom_name=f"{ns}_replica_active",
            help="in-flight decode rows from the replica's last status")
        self.replica_prefix_hits = Gauge(
            "fleet_replica_prefix_hits",
            prom_name=f"{ns}_replica_prefix_hits",
            help="prefix-cache hits from the replica's last status "
                 "(absent series = replica runs no prefix cache)")
        self.replica_alerts = Gauge(
            "fleet_replica_alerts_active",
            prom_name=f"{ns}_replica_alerts_active",
            help="1 while the replica reports this burn-rate alert "
                 "active in its /healthz alerts block, 0 once cleared "
                 "(labels: replica, rule, slo_class)")
        reg = registry or get_registry()
        reg.register_all([
            self.requests, self.http_requests, self.retries, self.shed,
            self.breaker_opens, self.stream_aborts, self.ttft,
            self.replica_healthy, self.replica_free_pages,
            self.replica_queue_depth, self.replica_active,
            self.replica_prefix_hits, self.replica_alerts,
        ])


class ReplicaState:
    """Router-side view of one replica."""

    def __init__(self, index, host, port):
        self.index = index
        self.host = host
        self.port = int(port)
        self.status = None          # last /healthz JSON
        self.status_time = 0.0
        self.healthy = False
        self.draining = False
        self.in_flight = 0          # router-side routed-not-finished
        self.failures = 0           # consecutive request-path failures
        self.breaker_open_until = 0.0
        self.requests_routed = 0
        # (rule, slo_class) pairs seen active in the last scrape — the
        # set difference drives 1 -> 0 gauge transitions on clear
        self.alert_keys = set()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def summary(self, now):
        st = self.status or {}
        return {
            "index": self.index,
            "host": self.host,
            "port": self.port,
            "healthy": self.healthy,
            "draining": self.draining,
            "breaker_open": now < self.breaker_open_until,
            "in_flight_routed": self.in_flight,
            "requests_routed": self.requests_routed,
            "status_age_s": (None if not self.status_time
                             else round(now - self.status_time, 3)),
            "free_pages": st.get("free_pages"),
            "queue_depth": st.get("queue_depth"),
            "active": st.get("active"),
            "generation": st.get("generation"),
            "weights_version": st.get("weights_version"),
            "last_reload_step": st.get("last_reload_step"),
            "reload_in_progress": st.get("reload_in_progress"),
            "compile_cache_hits": st.get("compile_cache_hits"),
            "prefix_cache": st.get("prefix_cache"),
            "alerts": st.get("alerts"),
        }


def _parse_replica(spec):
    if isinstance(spec, (tuple, list)):
        return str(spec[0]), int(spec[1])
    host, _, port = str(spec).rpartition(":")
    return (host or "127.0.0.1"), int(port)


class FleetRouter:
    """Route ``/v1/generate`` across N engine replicas.

    ``replicas`` is a list of ``(host, port)`` pairs or
    ``"host:port"`` strings — each the address of a
    :class:`~..http_frontend.ServingFrontend`. ``port=0`` binds the
    router on an ephemeral port (read ``.port`` back)."""

    def __init__(self, replicas, *, host="127.0.0.1", port=0,
                 registry=None, health_interval_s=0.25,
                 status_ttl_s=3.0, breaker_threshold=3,
                 breaker_cooldown_s=2.0, connect_timeout_s=5.0,
                 stream_timeout_s=120.0, clock=time.monotonic,
                 watch_ckpt_root=None, watch_interval_s=1.0,
                 watch_drain_timeout_s=120.0, affinity_bonus=0.5,
                 affinity_prefix_tokens=32, affinity_map_size=4096):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = [
            ReplicaState(i, *_parse_replica(s))
            for i, s in enumerate(replicas)
        ]
        self.host = host
        self.port = int(port)
        self.metrics = RouterMetrics(registry=registry)
        self.health_interval_s = float(health_interval_s)
        self.status_ttl_s = float(status_ttl_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stream_timeout_s = float(stream_timeout_s)
        self.clock = clock
        # cache-affinity placement: prompt-prefix head -> replica index
        # that last served it (bounded LRU; a prefix-cache hit there is
        # near-free, so its load score earns a bonus)
        self.affinity_bonus = float(affinity_bonus)
        self.affinity_prefix_tokens = int(affinity_prefix_tokens)
        self.affinity_map_size = int(affinity_map_size)
        self._affinity = collections.OrderedDict()
        # the router owns its OWN tracer (not the process default): it
        # must show up as a distinct "router" process row even when it
        # runs in-process next to an engine (serve_bench, smokes)
        self.tracer = Tracer(process="router")
        self._lock = threading.Lock()
        # one rolling reload at a time: overlapping walks would drain
        # multiple replicas at once, breaking the at-most-one-out-of-
        # rotation invariant (a retried admin POST must get a 409, not
        # a second concurrent walk)
        self._reload_walk_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd = None
        self._http_thread = None
        self._scrape_thread = None
        # checkpoint-root auto-rotation: poll latest_committed and run
        # the rolling walk on a NEW commit — publishing a checkpoint
        # then needs zero admin POSTs
        self.watch_ckpt_root = (
            str(watch_ckpt_root) if watch_ckpt_root else None
        )
        self.watch_interval_s = float(watch_interval_s)
        self.watch_drain_timeout_s = float(watch_drain_timeout_s)
        self._watch_thread = None
        self._watched_step = None
        self.last_watch_result = None
        # opt-in runtime lock sentinel (PADDLE_TPU_LOCK_SENTINEL=1)
        from ...analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)

    # ---------------------------------------------------------- lifecycle
    def start(self):
        from ..httpd import start_http_server

        # one synchronous scrape first, so the router can place
        # requests the moment start() returns
        self._scrape_all()
        self._httpd, self._http_thread = start_http_server(
            self.host, self.port, self._handle_get, self._handle_post,
            name="paddle-fleet-http",
        )
        self.port = self._httpd.server_address[1]
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="paddle-fleet-scrape",
            daemon=True,
        )
        self._scrape_thread.start()
        if self.watch_ckpt_root:
            # baseline = the newest commit ALREADY on disk: the fleet
            # is assumed launched from it, only new commits rotate
            found = self._latest_commit()
            with self._lock:
                self._watched_step = found[0] if found else None
            self._watch_thread = threading.Thread(
                target=self._watch_ckpt_loop,
                name="paddle-fleet-ckpt-watch", daemon=True,
            )
            self._watch_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5)
            self._watch_thread = None
        from ..httpd import stop_http_server

        stop_http_server(self._httpd, self._http_thread)
        self._httpd = None
        self._http_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------- scrape
    def _scrape_one(self, r):
        import http.client

        try:
            conn = http.client.HTTPConnection(
                r.host, r.port, timeout=max(self.health_interval_s, 1.0)
            )
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            if resp.status != 200:
                raise OSError(f"healthz HTTP {resp.status}")
            status = json.loads(body)
        except (OSError, ValueError) as e:
            with self._lock:
                r.healthy = False
                r.status_time = self.clock()
                r.status = {"error": repr(e)}
            self.metrics.replica_healthy.set(0, replica=str(r.index))
            return
        with self._lock:
            r.status = status
            r.status_time = self.clock()
            r.healthy = bool(status.get("accepting", True))
            r.draining = bool(status.get("draining", False))
        m = self.metrics
        idx = str(r.index)
        m.replica_healthy.set(1 if r.healthy else 0, replica=idx)
        for gauge, field in (
            (m.replica_free_pages, "free_pages"),
            (m.replica_queue_depth, "queue_depth"),
            (m.replica_active, "active"),
        ):
            v = status.get(field)
            if v is not None:
                gauge.set(float(v), replica=idx)
        hits = (status.get("prefix_cache") or {}).get("hits")
        if hits is not None:
            m.replica_prefix_hits.set(float(hits), replica=idx)
        # burn-rate alert aggregation: mirror the replica's active set
        # into the router gauge, clearing (1 -> 0) series that vanished
        active = (status.get("alerts") or {}).get("active") or []
        keys = set()
        for a in active:
            if not isinstance(a, dict):
                continue
            key = (str(a.get("rule")), str(a.get("slo_class")))
            keys.add(key)
        with self._lock:
            prev, r.alert_keys = r.alert_keys, keys
        for rule, cls in keys:
            m.replica_alerts.set(1, replica=idx, rule=rule,
                                 slo_class=cls)
        for rule, cls in prev - keys:
            m.replica_alerts.set(0, replica=idx, rule=rule,
                                 slo_class=cls)

    def _scrape_all(self):
        # one thread per replica: a few unreachable hosts hanging to
        # their connect timeout must not age every HEALTHY replica's
        # status past status_ttl_s (serial scraping would shed the
        # whole fleet as stale)
        threads = [
            threading.Thread(target=self._scrape_one, args=(r,),
                             daemon=True)
            for r in self.replicas
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _scrape_loop(self):
        while not self._stop.wait(self.health_interval_s):
            self._scrape_all()

    # ---------------------------------------------------------- placement
    def _eligible(self, now, exclude=()):
        return [r for r, _ in self._eligible_snapshot(now, exclude)]

    def _eligible_snapshot(self, now, exclude=()):
        """Eligible replicas WITH their load-score inputs, all read
        under the lock: the scrape thread rewrites ``r.status`` /
        ``r.healthy`` concurrently, and scoring from unlocked reads
        mixes fields of two different scrapes (the health-map race the
        concurrency lint flags)."""
        out = []
        with self._lock:
            for r in self.replicas:
                if r.index in exclude:
                    continue
                if not r.healthy or r.draining:
                    continue
                if now < r.breaker_open_until:
                    continue
                if now - r.status_time > self.status_ttl_s:
                    continue
                st = r.status or {}
                out.append((r, (
                    float(st.get("queue_depth") or 0)
                    + float(st.get("active") or 0)
                    + float(r.in_flight),
                    float(st.get("free_pages") or 0),
                )))
        return out

    def _affinity_key(self, parsed):
        """Cache-affinity placement key: the session id when the body
        carries one (every turn of a chat lands on the replica holding
        its decode-published KV chain), else the prompt-prefix head
        (None when the body carries no usable input_ids)."""
        if isinstance(parsed, dict):
            sid = parsed.get("session_id")
            if isinstance(sid, str) and sid:
                return ("session", sid)
        ids = parsed.get("input_ids") if isinstance(parsed, dict) else None
        if not isinstance(ids, list) or not ids:
            return None
        try:
            return tuple(int(t) for t in
                         ids[:self.affinity_prefix_tokens])
        except (TypeError, ValueError):
            return None

    def _note_affinity(self, key, index):
        if key is None or self.affinity_bonus <= 0:
            return
        with self._lock:
            self._affinity[key] = index
            self._affinity.move_to_end(key)
            while len(self._affinity) > self.affinity_map_size:
                self._affinity.popitem(last=False)

    def _pick(self, exclude=(), affinity_key=None):
        """Least-loaded eligible replica, or None. Load folds the
        scraped queue depth + active rows (pressure) against free
        pages (capacity), plus the router's own in-flight count so two
        back-to-back requests don't pile onto one replica between
        scrapes. The replica that last served this prompt-prefix head
        gets its score divided by ``1 + affinity_bonus`` — a warm
        prefix cache makes it strictly cheaper there, until real load
        outweighs the bonus."""
        now = self.clock()
        affine = None
        if affinity_key is not None and self.affinity_bonus > 0:
            with self._lock:
                affine = self._affinity.get(affinity_key)
        best, best_score = None, None
        for r, (pressure0, free_pages) in self._eligible_snapshot(
            now, exclude
        ):
            pressure = 1.0 + pressure0
            capacity = 1.0 + free_pages
            score = pressure / capacity
            if affine == r.index:
                score /= 1.0 + self.affinity_bonus
            if best_score is None or score < best_score:
                best, best_score = r, score
        return best

    def _breaker_fail(self, r):
        with self._lock:
            r.failures += 1
            r.healthy = False  # next scrape may resurrect it
            if r.failures >= self.breaker_threshold:
                r.breaker_open_until = (self.clock()
                                        + self.breaker_cooldown_s)
                r.failures = 0
                opened = True
            else:
                opened = False
        self.metrics.replica_healthy.set(0, replica=str(r.index))
        if opened:
            self.metrics.breaker_opens.inc(label=str(r.index))

    def _breaker_ok(self, r):
        with self._lock:
            r.failures = 0
            r.breaker_open_until = 0.0

    # ----------------------------------------------------------- handlers
    def _send_json(self, h, code, obj):
        from ..httpd import send_json

        try:
            send_json(h, code, obj)
        except OSError:
            return
        self.metrics.http_requests.inc(label=str(code))

    def _handle_get(self, h):
        from ..httpd import send_text

        path = h.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                send_text(
                    h, 200, prometheus_text().encode("utf-8"),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.metrics.http_requests.inc(label="200")
            elif path == "/trace":
                self._send_json(h, 200, trace_payload(self.tracer))
            elif path == "/alerts":
                # fleet-wide SLO view: every replica's active alert
                # block from the last /healthz scrape, in one response
                with self._lock:
                    reps = [
                        {
                            "index": r.index,
                            "host": r.host,
                            "port": r.port,
                            "alerts": (r.status or {}).get("alerts"),
                        }
                        for r in self.replicas
                    ]
                total = sum(
                    len(((rep["alerts"] or {}).get("active")) or [])
                    for rep in reps
                )
                self._send_json(h, 200, {
                    "role": "fleet-router",
                    "active_total": total,
                    "replicas": reps,
                })
            elif path in ("/healthz", "/replicas"):
                now = self.clock()
                reps = [r.summary(now) for r in self.replicas]
                self._send_json(h, 200, {
                    "role": "fleet-router",
                    "replicas": reps,
                    "eligible": len(self._eligible(now)),
                })
            else:
                self._send_json(h, 404, {"error": "not found"})
        except Exception as e:
            try:
                self._send_json(h, 500, {"error": repr(e)})
            except Exception:
                pass

    def _handle_post(self, h):
        path = h.path.split("?", 1)[0]
        if path.startswith("/admin/drain/") \
                or path.startswith("/admin/undrain/"):
            self._handle_admin_drain(h, path)
            return
        if path == "/admin/reload":
            self._handle_admin_reload(h)
            return
        if path != "/v1/generate":
            self._send_json(h, 404, {"error": "not found"})
            return
        try:
            n = int(h.headers.get("Content-Length", 0))
            body = h.rfile.read(n) or b"{}"
            parsed = json.loads(body)
            if not isinstance(parsed, dict):
                raise ValueError("body must be a JSON object")
        except Exception as e:
            self._send_json(h, 400, {"error": f"bad request: {e}"})
            return
        stream = bool(parsed.get("stream", True))
        try:
            self._route(h, body, stream, parsed)
        except Exception as e:
            # last-ditch: the client must get a status or a terminal
            # event, never a silently dropped connection
            try:
                self._send_json(h, 502, {"error": repr(e)})
            except Exception:
                pass

    def _handle_admin_drain(self, h, path):
        import http.client

        undo = path.startswith("/admin/undrain/")
        try:
            idx = int(path.rsplit("/", 1)[1])
            r = self.replicas[idx]
        except (ValueError, IndexError):
            self._send_json(h, 404, {"error": "no such replica"})
            return
        try:
            conn = http.client.HTTPConnection(
                r.host, r.port, timeout=self.connect_timeout_s
            )
            conn.request("POST", "/undrain" if undo else "/drain")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            replica_resp = json.loads(body or b"{}")
        except (OSError, ValueError) as e:
            self._send_json(h, 502, {"error": repr(e),
                                     "replica": idx})
            return
        # stop/resume routing immediately; the scrape loop keeps the
        # flag in sync with the replica's own report afterwards
        with self._lock:
            r.draining = not undo
        self._send_json(h, 200, {"replica": idx,
                                 "draining": not undo,
                                 "replica_response": replica_resp})

    # ----------------------------------------------------- rolling reload
    def _replica_call(self, r, method, path, body=None, timeout=None):
        """One HTTP exchange with a replica; raises OSError-family on
        transport trouble. Returns ``(status, parsed_json)``."""
        import http.client

        conn = http.client.HTTPConnection(
            r.host, r.port,
            timeout=timeout if timeout is not None
            else self.connect_timeout_s,
        )
        try:
            payload = None if body is None else json.dumps(body)
            conn.request(
                method, path, body=payload,
                headers={"Content-Type": "application/json"}
                if payload is not None else {},
            )
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        try:
            parsed = json.loads(raw or b"{}")
        except ValueError:
            parsed = {"raw": raw.decode("utf-8", "replace")}
        return resp.status, parsed

    def _reload_replica(self, r, ckpt_dir, version, drain_timeout_s):
        """drain -> wait idle -> /reload -> undrain, for one replica.
        The undrain runs in ``finally`` so a failed reload leaves the
        replica back in rotation (on its OLD weights) instead of
        silently out of the fleet."""
        import http.client

        _err = (OSError, http.client.HTTPException)
        out = {"replica": r.index, "ok": False}
        # a replica the operator ALREADY drained (maintenance, debug)
        # stays drained after its reload — the walk only undoes its
        # own drain, never a deliberate prior one. When the probe
        # itself fails, fall back to the router's own view (the scrape
        # loop mirrors the replica's flag) rather than assuming False
        # and undraining a deliberately-removed replica.
        try:
            _, st0 = self._replica_call(r, "GET", "/healthz")
            was_draining = bool(st0.get("draining", False))
        except _err:
            was_draining = bool(r.draining)
        with self._lock:
            r.draining = True
        try:
            # the drain POST runs INSIDE the undrain guard: if it was
            # applied but its response got lost, the finally still
            # puts the replica back in rotation (an undrain the
            # replica never needed is harmless)
            try:
                self._replica_call(r, "POST", "/drain")
            except _err as e:
                out.update(stage="drain", error=repr(e))
                return out
            # admin-walk deadline on REAL time: the injectable clock
            # drives placement/breaker logic (tests advance it
            # manually), and pacing below sleeps real seconds — mixing
            # the two would make the timeout unreachable
            deadline = time.monotonic() + float(drain_timeout_s)
            idle = False
            while time.monotonic() < deadline:
                if self._stop.is_set():
                    # router shutting down mid-walk: unwind NOW so the
                    # finally below undrains this replica before the
                    # process exits — a drain-wait that outlives
                    # stop()'s join would strand it out of rotation
                    out.update(stage="router_stopped",
                               error="router stopped during drain wait")
                    return out
                try:
                    _, st = self._replica_call(r, "GET", "/healthz")
                except _err:
                    st = {}
                if (st.get("active", 1) == 0
                        and st.get("queue_depth", 1) == 0):
                    idle = True
                    break
                time.sleep(0.05)
            if not idle:
                out.update(stage="drain_timeout",
                           error=f"replica {r.index} not idle within "
                                 f"{drain_timeout_s}s")
                return out
            try:
                # reload prepare reads + verifies the checkpoint from
                # disk — give it the stream budget, not the connect one
                code, res = self._replica_call(
                    r, "POST", "/reload",
                    body={"ckpt_dir": ckpt_dir,
                          "weights_version": version},
                    timeout=self.stream_timeout_s,
                )
            except _err as e:
                out.update(stage="reload", error=repr(e))
                return out
            if code != 200 or not res.get("ok", False):
                out.update(stage="reload", status=code,
                           error=res.get("error") or res)
                out["outcome"] = res.get("outcome")
                return out
            out.update(
                ok=True, outcome=res.get("outcome"),
                weights_version=res.get("weights_version"),
                step=res.get("step"), applied=res.get("applied"),
            )
            return out
        finally:
            if was_draining:
                out["kept_drained"] = True
            else:
                try:
                    self._replica_call(r, "POST", "/undrain")
                except _err as e:
                    # a replica stuck draining IS a failed rotation
                    # step: the walk must STOP (out is mutated after
                    # the return — the caller sees ok=False), or it
                    # would drain the next replica with this one
                    # still out of rotation
                    out["undrain_error"] = repr(e)
                    out["ok"] = False
                    out.setdefault("stage", "undrain")
                with self._lock:
                    r.draining = False
                # re-scrape NOW: the walk must not drain the next
                # replica while this one still carries its stale
                # draining/unhealthy status — that window is the one
                # place a 2-replica rotation could shed no_replicas
                self._scrape_one(r)

    def _handle_admin_reload(self, h):
        """``POST /admin/reload {"ckpt_dir": ...}`` — the zero-downtime
        rotation: walk the fleet one replica at a time, drain -> swap
        -> undrain. At most ONE replica is ever out of rotation, so
        in-flight streams finish where they run and new requests place
        on the rest of the fleet — zero dropped requests. Stops at the
        first failed replica (a bad checkpoint must not take the whole
        fleet); already-rotated replicas keep the new weights, the
        failed one is undrained on its old weights."""
        try:
            n = int(h.headers.get("Content-Length", 0))
            body = json.loads(h.rfile.read(n) or b"{}")
            ckpt_dir = body["ckpt_dir"]
            if not isinstance(ckpt_dir, str) or not ckpt_dir:
                raise ValueError("ckpt_dir must be a non-empty string")
            version = body.get("weights_version")
            drain_timeout_s = float(body.get("drain_timeout_s", 120.0))
        except Exception as e:
            self._send_json(h, 400, {"error": f"bad request: {e}"})
            return
        out = self.reload_fleet(ckpt_dir, version=version,
                                drain_timeout_s=drain_timeout_s)
        if out is None:
            self._send_json(h, 409, {
                "error": "rejected",
                "reason": "reload_in_progress",
            })
            return
        self._send_json(h, 200 if out["ok"] else 500, out)

    def reload_fleet(self, ckpt_dir, version=None,
                     drain_timeout_s=120.0):
        """Run one rolling reload walk (drain -> swap -> undrain, one
        replica at a time — the ``/admin/reload`` body). Returns the
        ``{"ok": ..., "results": [...]}`` record, or None when a walk
        is already in progress (the admin handler maps that to 409,
        the checkpoint watcher just retries on its next poll)."""
        if not self._reload_walk_lock.acquire(blocking=False):
            return None
        try:
            results = []
            for r in self.replicas:
                res = self._reload_replica(r, ckpt_dir, version,
                                           drain_timeout_s)
                results.append(res)
                if not res["ok"]:
                    break
            ok = all(res["ok"] for res in results) and \
                len(results) == len(self.replicas)
        finally:
            self._reload_walk_lock.release()
        return {"ok": ok, "results": results}

    # ------------------------------------------------ checkpoint watching
    def _latest_commit(self):
        """Newest COMMITTED checkpoint under the watched root as
        ``(step, path)``, or None. Manifest-committed generations only
        (``latest_committed`` — a torn/in-flight save can never
        trigger a rotation)."""
        from ...checkpoint import commit as commit_mod

        try:
            path = commit_mod.latest_committed(self.watch_ckpt_root)
            if path is None:
                return None
            manifest = commit_mod.read_manifest(path)
            if manifest is None:
                return None
            return int(manifest["step"]), path
        except Exception:
            return None

    def _watch_ckpt_loop(self):
        while not self._stop.wait(self.watch_interval_s):
            found = self._latest_commit()
            if found is None:
                continue
            step, path = found
            with self._lock:
                watched = self._watched_step
            if watched is not None and step <= watched:
                continue
            out = self.reload_fleet(
                path, version=None,
                drain_timeout_s=self.watch_drain_timeout_s,
            )
            if out is None:
                continue  # a walk was in flight; retry next poll
            # watcher-thread publications go under the lock: admin
            # readers (/replicas, tests) poll these from other threads
            with self._lock:
                self.last_watch_result = dict(out, step=step, path=path)
                if out["ok"]:
                    # only a fully-rotated fleet advances the marker: a
                    # failed walk is retried on the next poll (replicas
                    # already rotated are version-idempotent)
                    self._watched_step = step

    # ------------------------------------------------------------ routing
    def _route(self, h, body, stream, parsed=None):
        # head-sampling point for the whole distributed trace: the root
        # span starts here (or not at all); everything downstream —
        # frontend, engine, KV wire, worker — hangs off its context
        rsp = self.tracer.start_trace("router.request",
                                      stream=bool(stream))
        try:
            attrs = self._route_attempts(h, body, stream, parsed, rsp)
        except BaseException:
            if rsp is not None:
                rsp.finish(outcome="error", error="router_error")
            raise
        if rsp is not None:
            rsp.finish(**attrs)

    def _route_attempts(self, h, body, stream, parsed, rsp):
        """The placement/retry loop; returns the root span's outcome
        attributes (``error=`` present on shed/abort paths)."""
        t_recv = self.clock()
        tried = set()
        saw_saturated = False
        saw_conn_error = False
        akey = self._affinity_key(parsed or {})
        tid = None if rsp is None else rsp.trace_id
        client = _ClientStream(h, self.metrics, trace_id=tid)
        while True:
            r = self._pick(exclude=tried, affinity_key=akey)
            if r is None:
                break
            tried.add(r.index)
            # recorded at placement, not completion: concurrent
            # same-prefix requests converge on the warm replica now
            self._note_affinity(akey, r.index)
            with self._lock:
                r.in_flight += 1
            try:
                outcome = self._try_replica(r, client, body, stream,
                                            t_recv, rsp)
            finally:
                with self._lock:
                    r.in_flight -= 1
            if outcome == "done":
                self._breaker_ok(r)
                return {"outcome": "done", "replica": r.index,
                        "attempts": len(tried)}
            if outcome == "client_gone":
                return {"outcome": "client_gone", "replica": r.index,
                        "attempts": len(tried),
                        "error": ABORT_CLIENT_DISCONNECT}
            if outcome == "failed_after_tokens":
                # terminal error already sent; never replayed
                self._breaker_fail(r)
                return {"outcome": "failed_after_tokens",
                        "replica": r.index, "attempts": len(tried),
                        "error": ABORT_REPLICA_FAILED}
            if outcome == "saturated":
                saw_saturated = True
                self.metrics.retries.inc(label="replica_busy",
                                         trace_id=tid)
                continue
            if outcome in ("conn_error", "midstream_unstarted"):
                # midstream_unstarted already counted its retry label
                # in _pipe_sse — one retry event, one sample
                saw_conn_error = True
                self._breaker_fail(r)
                if outcome == "conn_error":
                    self.metrics.retries.inc(label="conn_error",
                                             trace_id=tid)
                continue
            raise AssertionError(f"unknown outcome {outcome!r}")
        # fleet exhausted: shed with a reason that tells the client
        # (and the load balancer above us) what to do about it
        if saw_saturated:
            reason = SHED_FLEET_SATURATED
        elif saw_conn_error:
            reason = SHED_REPLICAS_UNAVAILABLE
        else:
            reason = SHED_NO_REPLICAS
        self.metrics.shed.inc(label=reason, trace_id=tid)
        if client.headers_sent:
            # stream already open (a replica died mid-handshake after
            # we committed to SSE): terminal error event, not a status
            client.error_event({"reason": reason})
            self.metrics.stream_aborts.inc(label=reason, trace_id=tid)
        else:
            self._send_json(h, _SHED_STATUS[reason], {
                "error": "rejected", "reason": reason,
                "replicas_tried": len(tried),
            })
        return {"outcome": "shed", "attempts": len(tried),
                "error": reason}

    def _try_replica(self, r, client, body, stream, t_recv, rsp=None):
        """One placement attempt. Returns 'done' | 'client_gone' |
        'failed_after_tokens' | 'saturated' | 'conn_error' |
        'midstream_unstarted'."""
        # per-attempt CLIENT span — its traceparent is what crosses the
        # HTTP hop, so the replica's server span parents under THIS
        # attempt, not under the whole request (retries stay separable)
        asp = None if rsp is None else self.tracer.start_span(
            "router.try_replica", rsp, replica=r.index
        )
        outcome = self._try_replica_once(r, client, body, stream,
                                         t_recv, asp)
        if asp is not None:
            bad = outcome in ("conn_error", "midstream_unstarted",
                              "failed_after_tokens", "saturated",
                              "client_gone")
            asp.finish(outcome=outcome,
                       **({"error": outcome} if bad else {}))
        return outcome

    def _try_replica_once(self, r, client, body, stream, t_recv, asp):
        import http.client

        # a replica dying mid-response surfaces as HTTPException
        # (BadStatusLine, IncompleteRead) — NOT an OSError subclass;
        # both mean the same thing here: replica trouble, retryable
        # while nothing reached the client
        _replica_err = (OSError, http.client.HTTPException)
        conn = http.client.HTTPConnection(
            r.host, r.port, timeout=self.connect_timeout_s
        )
        try:
            headers = {"Content-Type": "application/json"}
            if asp is not None:
                headers[TRACEPARENT_HEADER] = format_traceparent(asp)
            conn.request(
                "POST", "/v1/generate", body=body, headers=headers,
            )
            # connect is bounded by connect_timeout_s above; from here
            # on reads wait on GENERATION (a non-stream response only
            # arrives when decode finishes), so the stream timeout
            # governs — for every branch, not just SSE piping
            if conn.sock is not None:
                conn.sock.settimeout(self.stream_timeout_s)
            resp = conn.getresponse()
        except _replica_err:
            conn.close()
            return "conn_error"
        try:
            if resp.status != 200:
                try:
                    payload = resp.read()
                except _replica_err:
                    return "conn_error"
                if resp.status in (429, 503):
                    # replica backpressure / draining / closed: the
                    # request never started — try the next replica
                    return "saturated"
                if resp.status in (400, 413):
                    # the REQUEST's fault; identical on every replica
                    self._forward_reject(client, resp.status, payload)
                    return "done"
                return "conn_error"  # 5xx: replica trouble
            self.metrics.requests.inc(label=str(r.index))
            with self._lock:
                r.requests_routed += 1
            if not stream:
                try:
                    payload = resp.read()
                except _replica_err:
                    # nothing reached the client yet — retryable
                    return "conn_error"
                self._forward_reject(client, 200, payload)
                return "done"
            return self._pipe_sse(r, resp, client, t_recv, asp)
        finally:
            conn.close()

    def _forward_reject(self, client, code, payload):
        try:
            obj = json.loads(payload or b"{}")
        except ValueError:
            obj = {"raw": payload.decode("utf-8", "replace")}
        if client.headers_sent:
            # the SSE stream is already open (prior attempt died after
            # the handshake) — a status line now would corrupt it
            client.error_event(dict(obj, reason=obj.get(
                "reason", f"http_{code}")))
            return
        self._send_json(client.h, code, obj)

    def _pipe_sse(self, r, resp, client, t_recv, asp=None):
        """Forward the replica's SSE stream event-block by event-block.
        Token events count toward the unstarted/started boundary; a
        replica failure after the first forwarded token ends the
        client stream with a terminal error event instead of a retry.
        """
        import http.client

        tid = None if asp is None else asp.trace_id
        tokens_forwarded = 0
        try:
            for block, event in _iter_sse_blocks(resp):
                if not client.write(block):
                    return "client_gone"
                if event == "token":
                    if tokens_forwarded == 0:
                        self.metrics.ttft.observe(
                            self.clock() - t_recv, trace_id=tid,
                        )
                    tokens_forwarded += 1
                elif event in ("done", "error"):
                    return "done"
            # stream ended without a terminal event: replica died
            raise OSError("replica stream ended mid-request")
        except (OSError, http.client.HTTPException):
            if tokens_forwarded == 0:
                # unstarted — safe to replay on another replica
                self.metrics.retries.inc(label="midstream_unstarted",
                                         trace_id=tid)
                return "midstream_unstarted"
            client.error_event({
                "reason": ABORT_REPLICA_FAILED,
                "replica": r.index,
                "tokens_forwarded": tokens_forwarded,
            })
            self.metrics.stream_aborts.inc(label=ABORT_REPLICA_FAILED,
                                           trace_id=tid)
            return "failed_after_tokens"


class _ClientStream:
    """The router's half-open SSE response: headers sent lazily at the
    first forwarded block, so an unstarted request can still fail over
    to another replica (or shed with a plain HTTP status)."""

    def __init__(self, h, metrics, trace_id=None):
        self.h = h
        self.metrics = metrics
        self.trace_id = trace_id
        self.headers_sent = False
        self.client_gone = False

    def _send_headers(self):
        self.h.send_response(200)
        self.h.send_header("Content-Type", "text/event-stream")
        self.h.send_header("Cache-Control", "no-cache")
        self.h.send_header("Connection", "close")
        self.h.end_headers()
        self.headers_sent = True
        self.metrics.http_requests.inc(label="200")

    def write(self, block):
        """Forward one SSE event block; False when the client is gone
        (the caller aborts the upstream read)."""
        if self.client_gone:
            return False
        try:
            if not self.headers_sent:
                self._send_headers()
            self.h.wfile.write(block)
            self.h.wfile.flush()
            return True
        except OSError:
            self.client_gone = True
            self.metrics.stream_aborts.inc(
                label=ABORT_CLIENT_DISCONNECT, trace_id=self.trace_id,
            )
            return False

    def error_event(self, payload):
        if self.client_gone:
            return
        try:
            if not self.headers_sent:
                self._send_headers()
            data = json.dumps(payload)
            self.h.wfile.write(
                f"event: error\ndata: {data}\n\n".encode("utf-8")
            )
            self.h.wfile.flush()
        except OSError:
            self.client_gone = True


def _iter_sse_blocks(fp):
    """Yield ``(raw_block_bytes, event_name)`` per SSE event from a
    replica response — raw bytes so forwarding is byte-faithful, the
    event name so the router can track the token/terminal boundary.

    A tail without its blank-line terminator is a TRUNCATED block (the
    replica died mid-write) and is deliberately dropped — forwarding
    half a ``data:`` line would corrupt the client's stream right
    before the terminal error event; complete blocks always flush
    inside the loop because writers end every event with ``\\n\\n``."""
    lines = []
    event = None
    for raw in fp:
        line = raw.decode("utf-8", "replace").rstrip("\n").rstrip("\r")
        if line:
            lines.append(raw)
            if line.startswith("event:"):
                event = line[6:].strip()
            continue
        if lines:
            yield b"".join(lines) + b"\n", event
            lines, event = [], None
