"""Shared stdlib HTTP scaffolding for the serving front-ends.

`ServingFrontend` and the fleet `FleetRouter` are both thin
threading-HTTP servers; the server subclass (daemon handler threads +
a burst-safe listen backlog), the handler shim, the lifecycle thread
and the JSON responder live HERE so a server-level fix lands once.
"""
from __future__ import annotations

import http.server
import json
import threading


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True  # streaming handlers must not pin shutdown
    # socketserver's default listen backlog of 5 drops SYNs under a
    # concurrent-connect burst — the kernel's ~1s SYN retransmit then
    # dominates every latency percentile
    request_queue_size = 128


def start_http_server(host, port, on_get, on_post, name):
    """Bind + serve on a daemon thread. Returns ``(httpd, thread)``;
    read the ephemeral port back off ``httpd.server_address[1]``."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            on_get(self)

        def do_POST(self):
            on_post(self)

    httpd = _Server((host, int(port)), Handler)
    thread = threading.Thread(target=httpd.serve_forever, name=name,
                              daemon=True)
    thread.start()
    return httpd, thread


def stop_http_server(httpd, thread, timeout_s=10):
    if httpd is not None:
        httpd.shutdown()
        httpd.server_close()
    if thread is not None:
        thread.join(timeout=timeout_s)


def send_json(h, code, obj):
    """One JSON response on handler ``h``. Raises OSError upward if
    the client is gone — callers decide whether that matters."""
    data = json.dumps(obj, default=str).encode("utf-8")
    h.send_response(code)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(data)))
    h.end_headers()
    h.wfile.write(data)


def send_text(h, code, body, content_type):
    h.send_response(code)
    h.send_header("Content-Type", content_type)
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)
