"""Request scheduling for the serving engine.

FIFO + priority admission over a bounded queue with explicit
backpressure: ``submit`` never blocks — a full queue or an infeasible
request is rejected immediately with a machine-readable reason, which
is what a front-end needs to shed load instead of letting latency run
away. Deadlines are absolute (clock-relative at submit): a request that
expires while queued is failed without ever touching the accelerator;
the engine also sweeps running requests each step so an expired
sequence frees its slot mid-decode (partial tokens are kept).

The scheduler is deliberately clock-injectable (``clock=``) so timeout
behavior is deterministically testable on CPU.
"""
from __future__ import annotations

import heapq
import itertools
import time

# rejection / completion reasons (machine-readable, stable strings)
REASON_QUEUE_FULL = "queue_full"
REASON_TOO_LONG = "too_long"
REASON_SHAPE_MISMATCH = "shape_mismatch"
REASON_TIMEOUT = "timeout"
REASON_ENGINE_CLOSED = "engine_closed"
# demand-grown paged decode: a mid-decode page claim that neither the
# freelist nor prefix-cache eviction could satisfy sheds the request
# with this reason (partial tokens kept, terminal event fired) — an
# overcommitted arena degrades one request, never crashes the engine
REASON_PAGES_EXHAUSTED = "pages_exhausted"

# request lifecycle states
QUEUED = "QUEUED"
RUNNING = "RUNNING"
DONE = "DONE"
REJECTED = "REJECTED"
TIMEOUT = "TIMEOUT"
CANCELLED = "CANCELLED"


class RejectedError(RuntimeError):
    """Raised by ``submit`` on backpressure; ``.reason`` is one of the
    REASON_* constants."""

    def __init__(self, reason, detail=""):
        super().__init__(f"request rejected ({reason}): {detail}")
        self.reason = reason


class Request:
    """One decode request: a prompt plus its generation budget."""

    _ids = itertools.count()

    def __init__(self, input_ids, max_new_tokens, *, eos_token_id=None,
                 priority=0, deadline_s=None, slo_class=None,
                 session_id=None):
        import numpy as np

        ids = np.asarray(input_ids)
        if ids.ndim == 2:
            if ids.shape[0] != 1:
                raise ValueError(
                    "a Request is ONE sequence; got batch "
                    f"{ids.shape[0]} (submit one Request per row)"
                )
            ids = ids[0]
        self.input_ids = ids.astype(np.int32)
        self.prompt_len = int(ids.shape[-1])
        if self.prompt_len < 1:
            raise ValueError("a Request needs at least one prompt token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.priority = int(priority)
        self.deadline_s = deadline_s  # relative seconds; resolved at submit
        # SLO traffic class (observability.slo): labels the latency
        # histograms and the trace root. The scheduler itself is
        # class-blind today — budget-aware admission is the follow-up.
        if slo_class is None:
            from ..observability.slo import DEFAULT_CLASS

            slo_class = DEFAULT_CLASS
        self.slo_class = str(slo_class)
        # conversation identity (serving.sessions): labels this request
        # as one turn of a chat session — session bookkeeping, router
        # affinity, and decode-publish chain continuity key on it. None
        # = a standalone request, served exactly as before.
        self.session_id = None if session_id is None else str(session_id)
        self.request_id = next(Request._ids)

    @property
    def total_tokens(self):
        return self.prompt_len + self.max_new_tokens


class RequestHandle:
    """The caller's view of a submitted request: status, tokens, and
    per-request timing, filled in as the engine progresses.

    Streaming surface: ``on_token(tok, handle)`` fires per emitted
    token, ``on_event(handle)`` fires EXACTLY ONCE when the handle
    reaches a terminal state (DONE/REJECTED/TIMEOUT/CANCELLED) — from
    wherever the transition happens: decode, submit-time reject, lazy
    queue expiry, or ``engine.close()``. That single-fire guarantee is
    what lets an SSE stream end with a terminal event instead of a
    silent hang when its request is shed. Callback exceptions are
    swallowed (a broken consumer must never wedge the engine loop)."""

    def __init__(self, request, on_token=None, on_event=None):
        self.request = request
        self.status = QUEUED
        self.reason = None          # set for REJECTED / TIMEOUT
        self.tokens = []            # emitted token ids (ints)
        self.submit_time = None
        self.admit_time = None      # wall time of admission (prefill)
        self.finish_time = None
        self.first_token_time = None
        self.admitted_step = None   # engine step index at admission
        self.finished_step = None
        self.weights_version = None  # engine weights at admission
        # distributed-tracing context (an observability.tracing.Span or
        # None): set by the front-end right after submit, read by the
        # engine at admission. None = sampled out — every engine
        # instrumentation site then allocates nothing.
        self.trace = None
        self._decode_span = None  # the engine's open per-request span
        self.on_token = on_token
        self.on_event = on_event
        self._terminal_fired = False

    @property
    def finished(self):
        return self.status in (DONE, REJECTED, TIMEOUT, CANCELLED)

    def _fire_token(self, tok):
        if self.on_token is not None:
            try:
                self.on_token(int(tok), self)
            except Exception:
                pass

    def _fire_terminal(self):
        """Notify the terminal transition exactly once (idempotent —
        every status-setting site calls this defensively)."""
        if self._terminal_fired:
            return
        self._terminal_fired = True
        if self.on_event is not None:
            try:
                self.on_event(self)
            except Exception:
                pass

    @property
    def output_ids(self):
        """prompt + generated tokens as one int32 numpy array."""
        import numpy as np

        return np.concatenate(
            [self.request.input_ids,
             np.asarray(self.tokens, np.int32)]
        ).astype(np.int32)

    @property
    def ttft(self):
        if self.first_token_time is None or self.submit_time is None:
            return None
        return self.first_token_time - self.submit_time

    def __repr__(self):
        return (
            f"RequestHandle(id={self.request.request_id}, "
            f"status={self.status}, tokens={len(self.tokens)})"
        )


class Scheduler:
    """Bounded priority+FIFO admission queue.

    Pop order: highest ``priority`` first, FIFO within a priority
    (heap key ``(-priority, seq)``). ``pop_next`` enforces the caller's
    token budget WITHOUT skipping past the head — strict ordering means
    a big request is delayed, never starved. Expired-deadline requests
    are failed lazily at pop time (and via ``sweep_expired``)."""

    def __init__(self, max_queue_size=64, clock=time.monotonic):
        self.max_queue_size = int(max_queue_size)
        self.clock = clock
        self._heap = []  # (-priority, seq, handle)
        self._seq = itertools.count()
        # handles that expired while queued, awaiting a metrics drain
        # (drain_timed_out empties it — bounded by queue size per step)
        self._timed_out = []

    @property
    def depth(self):
        return len(self._heap)

    def submit(self, request, on_token=None, on_event=None):
        """Enqueue; returns a RequestHandle. Raises RejectedError when
        the queue is full (bounded-queue backpressure). Callbacks are
        attached BEFORE the bound check so a queue-full reject still
        fires the terminal event (no silent SSE hang)."""
        handle = RequestHandle(request, on_token=on_token,
                               on_event=on_event)
        handle.submit_time = self.clock()
        if len(self._heap) >= self.max_queue_size:
            handle.status = REJECTED
            handle.reason = REASON_QUEUE_FULL
            handle.finish_time = handle.submit_time
            handle._fire_terminal()
            err = RejectedError(
                REASON_QUEUE_FULL,
                f"queue holds {len(self._heap)}/{self.max_queue_size}",
            )
            err.handle = handle  # engines return this instead of raising
            raise err
        heapq.heappush(
            self._heap, (-request.priority, next(self._seq), handle)
        )
        return handle

    def _expire(self, handle, now):
        handle.status = TIMEOUT
        handle.reason = REASON_TIMEOUT
        handle.finish_time = now
        handle._fire_terminal()
        self._timed_out.append(handle)

    def drain_timed_out(self):
        """Return-and-clear every handle that expired while queued since
        the last drain (sweep_expired AND pop_next both expire lazily;
        this is the single channel engines count timeouts from — and the
        clear keeps a long-running server from accumulating handles)."""
        out, self._timed_out = self._timed_out, []
        return out

    def deadline_of(self, handle):
        d = handle.request.deadline_s
        return None if d is None else handle.submit_time + d

    def sweep_expired(self):
        """Fail every queued request whose deadline has passed; returns
        the expired handles (callers feed them to metrics)."""
        now = self.clock()
        keep, expired = [], []
        for item in self._heap:
            h = item[2]
            dl = self.deadline_of(h)
            if dl is not None and now > dl:
                self._expire(h, now)
                expired.append(h)
            else:
                keep.append(item)
        if expired:
            self._heap = keep
            heapq.heapify(self._heap)
        return expired

    def pop_next(self, token_budget=None, fits=None):
        """The next admissible request, or None. Strict priority-FIFO:
        if the head does not fit ``token_budget`` (sum of prompt +
        max_new tokens the engine may still take in flight), nothing is
        admitted this call. ``fits`` is an optional per-request
        feasibility predicate with the same no-skip discipline (the
        prefix-caching engine's page-need check, which depends on cache
        state a scalar budget cannot express) — a head failing it is
        delayed, never overtaken. Expired heads are failed and
        skipped."""
        while self._heap:
            neg_pri, seq, handle = self._heap[0]
            dl = self.deadline_of(handle)
            now = self.clock()
            if dl is not None and now > dl:
                heapq.heappop(self._heap)
                self._expire(handle, now)
                continue
            if (
                token_budget is not None
                and handle.request.total_tokens > token_budget
            ):
                return None
            if fits is not None and not fits(handle.request):
                return None
            heapq.heappop(self._heap)
            return handle
        return None
