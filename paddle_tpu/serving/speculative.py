"""Speculative decoding — draft-propose, one-shot verify, exact streams.

Decode's serial bottleneck is one full-model forward per emitted token.
Speculative decoding issues FEWER serial target steps: a cheap draft
model proposes K tokens autoregressively, then the target scores all
K+1 positions in ONE program launch and a host-side acceptance rule
keeps the longest valid prefix — every round emits between 1 and K+1
tokens with exactly one target-verify launch.

The verify program is the heart, and its construction is dictated by a
measured numerics fact (see ``tests/test_speculative.py``): a chunked
forward's LOGITS are not bitwise-equal to sequential decode logits
(fp32 ulp drift from the different matmul shapes), but its bf16 KV
WRITES are — bf16 rounding absorbs the drift. So the verify body runs
two passes in one jitted program:

1. **chunk-write**: the K+1 tokens ``[last, d_1..d_K]`` run through the
   cache path at positions ``[pos, pos+K]`` (head skipped) — this
   writes the same bf16 KV a sequential decode would have written;
2. **broadcast re-read**: the written block is broadcast to K+1 batch
   rows and ONE decode-shaped step scores row ``i`` at position
   ``pos+i`` — decode-shaped attention over decode-written KV, bitwise
   identical to vanilla decode logits (row independence across batch
   size is the engine's core pinned invariant).

int8 KV stores per-token fp32 SCALES, which keep the chunk pass's ulp
drift, so for quantized caches the verify body instead unrolls K+1
sequential decode sub-steps inside one program — the vanilla data flow
exactly (bitwise by construction), amortizing dispatch rather than
FLOPs. Greedy speculative streams are therefore EXACT-EQUAL to vanilla
decode on bf16 AND int8 engines (tier-1-pinned).

For ``temperature > 0`` acceptance is the Leviathan/Chen rejection
rule: accept ``d_i`` iff ``U < p(d_i)/q(d_i)``, resample the first
rejection from ``norm(max(p - q, 0))``, bonus-sample from ``p_K`` when
everything is accepted — the emitted distribution EQUALS vanilla
sampling (chi-square-pinned), with every uniform drawn from the
position-addressed key tree in ``sampling_keys`` so slab and paged
engines emit identical speculative sampled streams.

Drafts: a separate small llama, or the draft-free SELF-speculative
variant — ``exit_layer=N`` runs the target's first N layers + the
shared head through the ``LlamaModel.forward(exit_layer=)`` seam (its
own N-layer KV cache, zero extra weights).

Engine integration is per-row: with speculation bound, each engine
step runs one propose+verify round per active row through backend
hooks (``_spec_gather`` / ``_spec_adopt`` / ``_spec_reserve`` /
``_spec_rollback``) — the paged engine's verify runs through the
bucketed gather -> verify -> adopt-pages pipeline into pages the
request owns, demand-claims transient pages for the proposed tail and
releases the rejected tail's pages on rollback (zero-leak-pinned).
The whole speculative program inventory — draft prefill per bucket,
draft decode, steady-state verify per block width, the KV gather —
pre-compiles in ``engine.warmup()`` and persists through the AOT
compile cache (``jit/aot_cache.py``), so the first speculative round
pays zero compiles. Known gaps: no tree/Medusa multi-branch drafts;
per-row rounds trade batched-decode throughput for latency (the win
is measured at low concurrency).
"""
from __future__ import annotations

import copy

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..core import tape
from ..core.tensor import Tensor
from ..models.generation import (
    alloc_kv_caches,
    decode_step,
    filter_logits,
    prefill,
)
from ..observability.tracing import get_tracer
from ..quantization.kv import broadcast_rows
from .sampling_keys import ACCEPT, DRAFT, RESIDUAL, position_key, purpose_key


def _flatten(caches):
    return [a for kv in caches for a in kv]


def _unflatten(flat):
    return [(flat[2 * i], flat[2 * i + 1]) for i in range(len(flat) // 2)]


class _EarlyExitDraft:
    """The self-speculative draft: the target's first ``exit_layer``
    decoder layers + final norm + the shared lm_head, presented through
    the same callable surface ``prefill``/``decode_step`` drive. Its
    ``config`` is a truncated copy so draft caches allocate exactly
    ``exit_layer`` layer pairs."""

    def __init__(self, target, exit_layer):
        n = int(exit_layer)
        if not 1 <= n <= target.config.num_hidden_layers:
            raise ValueError(
                f"exit_layer {exit_layer} outside [1, "
                f"{target.config.num_hidden_layers}]"
            )
        self.target = target
        self.exit_layer = n
        self.config = copy.copy(target.config)
        self.config.num_hidden_layers = n

    def __call__(self, input_ids, attn_mask=None, caches=None, pos=None,
                 page_table=None):
        kw = {} if page_table is None else {"page_table": page_table}
        return self.target(input_ids, attn_mask, caches=caches, pos=pos,
                           exit_layer=self.exit_layer, **kw)

    def load_functional_state(self, params, buffers):
        self.target.load_functional_state(params, buffers)

    def eval(self):
        self.target.eval()


# ------------------------------------------------------- acceptance math
#
# Host-side and numpy/eager-jax only: the verify program returns raw
# logits rows; everything below is deterministic given those rows and
# the request's position-addressed keys, so both engines compute
# identical outcomes (the cross-backend determinism pin).


def _dist(row, temperature, top_k, top_p):
    """One logits row [V] -> normalized fp32 probabilities over the
    SAME filtered support the compiled sampling head uses."""
    f = np.asarray(filter_logits(jnp.asarray(row)[None, :],
                                 jnp.float32(temperature), top_k, top_p))[0]
    f = f - np.max(f)
    p = np.exp(f, dtype=np.float64)
    p[~np.isfinite(f)] = 0.0
    return p / p.sum()


def _sample(probs, key):
    """Exact inverse-CDF draw from ``probs`` with one uniform off
    ``key`` — the host mirror of one categorical draw."""
    u = float(jax.random.uniform(key))
    cdf = np.cumsum(probs)
    return int(min(np.searchsorted(cdf, u * cdf[-1], side="right"),
                   len(probs) - 1))


def accept_greedy(target_rows, props):
    """Greedy token-match acceptance: ``target_rows`` [K+1, V] are the
    verify logits at positions pos..pos+K, ``props`` the K draft
    tokens. Returns (accepted_count, emitted tokens) — always emits
    accepted + 1 (the correction/bonus token from the first unmatched
    row), so a round never stalls."""
    a = 0
    for i, d in enumerate(props):
        if int(np.argmax(target_rows[i])) != int(d):
            break
        a += 1
    emitted = [int(t) for t in props[:a]]
    emitted.append(int(np.argmax(target_rows[a])))
    return a, emitted


def accept_sampled(target_rows, draft_rows, props, request_key, pos,
                   temperature, top_k, top_p):
    """Rejection-sampling acceptance (Leviathan/Chen): the emitted
    token distribution is EXACTLY vanilla sampling from the filtered
    target distribution, position by position. ``target_rows`` [K+1,V],
    ``draft_rows`` [K, V] (the draft's proposal logits), ``props`` the
    K proposed tokens; position ``pos`` is the verify round's base (the
    token at pos is the last emitted one). Returns
    (accepted_count, emitted)."""
    a = 0
    emitted = []
    for i, d in enumerate(props):
        d = int(d)
        p = _dist(target_rows[i], temperature, top_k, top_p)
        q = _dist(draft_rows[i], temperature, top_k, top_p)
        u = float(jax.random.uniform(
            purpose_key(request_key, pos + i + 1, ACCEPT)
        ))
        if q[d] > 0 and u * q[d] <= p[d]:
            a += 1
            emitted.append(d)
            continue
        residual = np.maximum(p - q, 0.0)
        if residual.sum() <= 0:
            residual = p  # p == q exactly: any draw is distribution-true
        emitted.append(_sample(
            residual, purpose_key(request_key, pos + i + 1, RESIDUAL)
        ))
        return a, emitted
    # everything accepted: the bonus token comes from the verify's last
    # row — the VANILLA position key, so an all-accept round consumes
    # the same stream address vanilla decode would have
    p = _dist(target_rows[len(props)], temperature, top_k, top_p)
    emitted.append(_sample(
        p, position_key(request_key, pos + len(props) + 1)
    ))
    return a, emitted


# ------------------------------------------------------- verify programs


def build_verify_body(net, k1, sequential):
    """The one-launch verify program body over a ``[1, W]`` KV block:
    ``ids`` [1, k1] at positions [pos, pos+k1). ``sequential=False`` is
    the parallel two-pass construction (bf16/fp32 — chunk-write then
    broadcast re-read); ``sequential=True`` unrolls k1 decode sub-steps
    (int8 — per-token fp32 scales keep chunk-shape ulps, so the verify
    must BE the vanilla data flow). Returns (logits [k1, V], block)."""

    if sequential:
        def body(params, buffers, ids, flat_block, pos):
            net.load_functional_state(params, buffers)
            net.eval()
            p = jnp.asarray(pos, jnp.int32)
            caches = _unflatten(flat_block)
            rows = []
            for i in range(k1):
                lg, caches = decode_step(
                    net, ids[:, i:i + 1], caches, p + i
                )
                rows.append(lg)
            return jnp.concatenate(rows, 0), _flatten(caches)

        return body

    def body(params, buffers, ids, flat_block, pos):
        net.load_functional_state(params, buffers)
        net.eval()
        p = jnp.asarray(pos, jnp.int32)
        with tape.trace_scope(), tape.no_grad():
            _, caches = net.model(
                Tensor(ids), None, caches=_unflatten(flat_block), pos=p,
                apply_final_norm=False,
            )
        flat2 = _flatten(caches)
        rows = _unflatten([broadcast_rows(a, k1) for a in flat2])
        logits, _ = decode_step(
            net, jnp.transpose(ids), rows,
            p + jnp.arange(k1, dtype=jnp.int32),
        )
        return logits, flat2

    return body


class _DraftSlot:
    """Per-engine-row draft cache state. ``fed`` counts tokens the
    draft has consumed (cache positions [0, fed) are valid); -1 marks a
    retired/fresh row whose next round re-ingests the full context.
    The arrays persist across requests — stale content sits behind the
    position mask until overwritten, the slab-recycling discipline."""

    __slots__ = ("flat", "fed")

    def __init__(self):
        self.flat = None
        self.fed = -1


class SpeculativeDecoder:
    """Pairs a draft with the target inside a serving engine.

    ``draft``: a small causal LM sharing the target's tokenizer space,
    OR ``exit_layer=N`` for the draft-free self-speculative variant.
    ``k`` is the proposal depth — each round emits 1..k+1 tokens for
    one verify launch. Construct, pass as ``speculative=`` to either
    engine, and the engine binds it at init."""

    def __init__(self, draft=None, *, k=4, exit_layer=None,
                 draft_cache_dtype="bfloat16"):
        if (draft is None) == (exit_layer is None):
            raise ValueError(
                "pass exactly one of draft= (a small causal LM) or "
                "exit_layer= (self-speculative early exit)"
            )
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self.exit_layer = None if exit_layer is None else int(exit_layer)
        self.draft_cache_dtype = draft_cache_dtype
        self._draft_arg = draft
        self._eng = None
        self._draft = None
        self._dparams = None
        self._dbuffers = None
        self._draft_traced = set()
        self._draft_prefill_fns = {}
        self._draft_decode_fn = None
        self._verify_fns = {}
        self._slots = {}
        self._sequential = False
        # running stats (the /healthz block + stats())
        self.rounds = 0
        self.proposed = 0
        self.accepted = 0
        self.emitted = 0
        self.draft_ingests = 0

    @property
    def mode(self):
        return "self" if self.exit_layer is not None else "draft"

    # ------------------------------------------------------------ binding
    def bind(self, engine):
        """Attach to one engine (called from the engine's __init__):
        resolve the draft, snapshot its weights, and widen the
        engine's recompile-storm bar to the speculative program
        inventory (per-bucket draft prefill + per-width verify +
        draft decode)."""
        if self._eng is not None:
            raise RuntimeError(
                "SpeculativeDecoder is already bound to an engine"
            )
        self._eng = engine
        if self.exit_layer is not None:
            self._draft = _EarlyExitDraft(engine.net, self.exit_layer)
            # self-spec shares the target snapshot (refreshed on reload)
            self._dparams = engine._params
            self._dbuffers = engine._buffers
        else:
            self._draft = self._draft_arg
            if self._draft.config.vocab_size != engine.config.vocab_size:
                raise ValueError(
                    f"draft vocab {self._draft.config.vocab_size} != "
                    f"target vocab {engine.config.vocab_size}"
                )
            self._dparams = {
                k: p.value for k, p in self._draft.named_parameters()
            }
            self._dbuffers = {
                k: b.value for k, b in self._draft.named_buffers()
            }
        self._sequential = jnp.dtype(engine.cache_dtype) == jnp.int8
        # speculative program inventory: draft prefill per bucket,
        # verify per (block width, chunk length) — chunk length is
        # k+1 in steady state, smaller only on the last round(s) of a
        # request — plus draft decode and the gather program(s)
        # (per-bucket on the paged engine, warmed up front)
        nb = len(engine._warmup_buckets())
        engine.trace_guard.max_compiles += nb * (self.k + 3) + 4

    def unbind(self):
        """Engine close: drop compiled programs and draft state."""
        self._eng = None
        self._draft_prefill_fns.clear()
        self._draft_decode_fn = None
        self._verify_fns.clear()
        self._slots.clear()
        self._draft_traced.clear()

    def on_weights_swapped(self, engine):
        """Live reload landed: the self-speculative draft serves the
        NEW snapshot, and every draft cache (computed under the old
        weights) is invalidated — next rounds re-ingest."""
        if self.exit_layer is not None:
            self._dparams = engine._params
            self._dbuffers = engine._buffers
        for st in self._slots.values():
            st.fed = -1

    def reset_slot(self, slot):
        """Row retired (request finished/cancelled): the draft cache
        arrays stay (recycled behind the position mask), the state is
        marked fresh."""
        st = self._slots.get(slot)
        if st is not None:
            st.fed = -1

    def stats(self):
        return {
            "mode": self.mode,
            "k": self.k,
            "exit_layer": self.exit_layer,
            "sequential_verify": self._sequential,
            "rounds": self.rounds,
            "proposed": self.proposed,
            "accepted": self.accepted,
            "emitted": self.emitted,
            "draft_ingests": self.draft_ingests,
            "mean_accept_length": (
                round(self.emitted / self.rounds, 3) if self.rounds
                else None
            ),
        }

    def reset_stats(self):
        """Zero the running counters (serve_bench calls this after its
        off-the-clock warmup so acceptance stats cover only the timed
        replay)."""
        self.rounds = self.proposed = 0
        self.accepted = self.emitted = 0
        self.draft_ingests = 0

    def signature(self):
        """The AOT-cache key extra for ``spec_*`` programs: every knob
        that changes a traced speculative program body. A cache hit
        across different draft geometries would install the wrong
        executable."""
        sig = {
            "mode": self.mode,
            "k": self.k,
            "exit_layer": self.exit_layer,
            "draft_cache_dtype": str(self.draft_cache_dtype),
            "sequential": self._sequential,
        }
        if self.exit_layer is None and self._draft is not None:
            dc = self._draft.config
            sig["draft_model"] = {
                "vocab": int(dc.vocab_size),
                "hidden": int(dc.hidden_size),
                "inter": int(dc.intermediate_size),
                "layers": int(dc.num_hidden_layers),
                "heads": int(dc.num_attention_heads),
                "kv_heads": int(dc.kv_heads),
            }
        return sig

    # ------------------------------------------------------- AOT warmup
    def warmup(self, eng, cache, stats, buckets):
        """Pre-compile (or AOT-cache-load) the whole speculative
        inventory before first traffic — called from the engine's
        ``warmup()`` with its cache/stats so the programs ride the
        same persistence and show in the same ``program_memory``
        table. Warms: draft prefill per prompt bucket, the draft
        decode step, the verify program per (block width, k+1) plus
        the (width, 1) last-round shape, and the backend's KV gather
        program(s). Every compile lands on a trace-guard key recorded
        at build time, so a LATER compile on those keys is a storm
        finding."""
        dp, db = self._dparams, self._dbuffers
        dflat = _flatten(alloc_kv_caches(
            self._draft.config, 1, eng.max_seq_len,
            self.draft_cache_dtype,
        ))
        try:
            for b in buckets:
                eng._warm_one(
                    cache, f"spec_draft_prefill_b{b}",
                    ("spec_dprefill", b), self._draft_prefill(b),
                    (dp, db, jnp.zeros((1, b), jnp.int32), dflat,
                     jnp.int32(b)),
                    lambda comp, b=b: self._draft_prefill_fns
                    .__setitem__(b, comp), stats,
                )
            eng._warm_one(
                cache, "spec_draft_decode", ("spec_ddecode",),
                self._draft_decode(),
                (dp, db, jnp.zeros((1, 1), jnp.int32), dflat,
                 jnp.int32(0)),
                lambda comp: setattr(self, "_draft_decode_fn", comp),
                stats,
            )
            for w in eng._verify_widths(buckets):
                flatb = _flatten(alloc_kv_caches(
                    eng.config, 1, w, eng.cache_dtype,
                ))
                # the whole chunk ladder: k+1 in steady state, every
                # shorter length on a request's final rounds (k_eff
                # clamps to the tokens still owed)
                for k1 in range(1, self.k + 2):
                    eng._warm_one(
                        cache, f"spec_verify_w{w}_k{k1}",
                        ("spec_verify", w, k1),
                        self._verify_fn(w, k1),
                        (eng._params, eng._buffers,
                         jnp.zeros((1, k1), jnp.int32), flatb,
                         jnp.int32(0)),
                        lambda comp, w=w, k1=k1: self._verify_fns
                        .__setitem__((w, k1), comp), stats,
                    )
            eng._warm_spec_gather(cache, stats, buckets)
            # the lowerings above already swapped tracers through the
            # draft's imperative layers once — the first-trace restore
            # below covers them, so runtime _drun need not re-restore
            for b in buckets:
                self._draft_traced.add(("dprefill", b))
            self._draft_traced.add(("ddecode",))
        finally:
            self._restore_draft()

    # ------------------------------------------------- compiled programs
    def _restore_draft(self):
        self._draft.load_functional_state(self._dparams, self._dbuffers)
        self._draft.eval()

    def _drun(self, trace_key, fn, *args):
        """Run a draft program with the engine's restore-after-first-
        trace discipline — tracing swaps tracers into the draft's
        imperative layers (for self-spec those ARE the target's)."""
        out = fn(*args)
        if trace_key not in self._draft_traced:
            self._draft_traced.add(trace_key)
            self._restore_draft()
            if self.exit_layer is not None:
                # the trace ran through the target net: put the
                # ENGINE's concrete state back too
                self._eng._restore_net_state()
        return out

    def _draft_prefill(self, bucket):
        fn = self._draft_prefill_fns.get(bucket)
        if fn is not None:
            return fn
        draft = self._draft

        def body(params, buffers, ids, flat, length):
            draft.load_functional_state(params, buffers)
            draft.eval()
            _, caches = prefill(draft, ids, _unflatten(flat),
                                length=length)
            return _flatten(caches)

        fn = jax.jit(body)
        self._draft_prefill_fns[bucket] = fn
        self._eng.trace_guard.record_compile(
            "serving::spec_draft_prefill", bucket,
            origin="serving/speculative.py",
        )
        return fn

    def _draft_decode(self):
        if self._draft_decode_fn is not None:
            return self._draft_decode_fn
        draft = self._draft

        def body(params, buffers, tok, flat, pos):
            draft.load_functional_state(params, buffers)
            draft.eval()
            logits, caches = decode_step(draft, tok, _unflatten(flat),
                                         pos)
            return logits, _flatten(caches)

        self._draft_decode_fn = jax.jit(body)
        self._eng.trace_guard.record_compile(
            "serving::spec_draft_decode", 1,
            origin="serving/speculative.py",
        )
        return self._draft_decode_fn

    def _verify_fn(self, width, k1):
        """The verify program for a [1, width] block scoring k1
        positions. Sized to the EXACT chunk (no id padding): a padded
        chunk would write cache positions past the reserved span, and
        jax's clamped scatter would land those writes on valid KV."""
        fn = self._verify_fns.get((width, k1))
        if fn is not None:
            return fn
        body = build_verify_body(self._eng.net, k1, self._sequential)
        fn = jax.jit(body)
        self._verify_fns[(width, k1)] = fn
        self._eng.trace_guard.record_compile(
            "serving::spec_verify", (width, k1),
            origin="serving/speculative.py",
        )
        return fn

    # ---------------------------------------------------------- the round
    def _slot_state(self, slot):
        st = self._slots.get(slot)
        if st is None:
            st = self._slots[slot] = _DraftSlot()
        if st.flat is None:
            st.flat = _flatten(alloc_kv_caches(
                self._draft.config, 1, self._eng.max_seq_len,
                self.draft_cache_dtype,
            ))
        return st

    def _full_tok(self, seq, j):
        """Token at sequence position ``j`` (prompt ++ emitted)."""
        req = seq.handle.request
        if j < req.prompt_len:
            return int(req.input_ids[j])
        return int(seq.handle.tokens[j - req.prompt_len])

    def _propose(self, eng, slot, seq, pos, k_eff):
        """Draft side of one round: catch the draft cache up to
        ``pos`` tokens consumed, then propose ``k_eff`` tokens.
        Returns (proposals, draft logits rows)."""
        st = self._slot_state(slot)
        dp, db = self._dparams, self._dbuffers
        if st.fed < 0 or st.fed > pos:
            # fresh row (or invalidated): ingest the full context
            # [0, pos) through the bucketed draft prefill
            bucket = eng.pool.bucket_for(pos)
            ids = np.zeros((1, bucket), np.int32)
            for j in range(pos):
                ids[0, j] = self._full_tok(seq, j)
            with profiler.RecordEvent(
                f"serving::spec_draft_prefill_b{bucket}"
            ):
                st.flat = self._drun(
                    ("dprefill", bucket), self._draft_prefill(bucket),
                    dp, db, jnp.asarray(ids), st.flat, jnp.int32(pos),
                )
            st.fed = pos
            self.draft_ingests += 1
        while st.fed < pos:
            # catch-up (at most one token per round: only a fully
            # accepted round leaves the bonus token unconsumed)
            _, st.flat = self._drun(
                ("ddecode",), self._draft_decode(), dp, db,
                jnp.asarray([[self._full_tok(seq, st.fed)]], jnp.int32),
                st.flat, jnp.int32(st.fed),
            )
            st.fed += 1
        props, qrows = [], []
        t = seq.last_tok
        do_sample = eng.do_sample
        for i in range(k_eff):
            lg, st.flat = self._drun(
                ("ddecode",), self._draft_decode(), dp, db,
                jnp.asarray([[t]], jnp.int32), st.flat,
                jnp.int32(pos + i),
            )
            st.fed = pos + i + 1
            row = np.asarray(lg[0])
            if do_sample:
                d = _sample(
                    _dist(row, eng.temperature, eng.top_k, eng.top_p),
                    purpose_key(jnp.asarray(seq.key), pos + i + 1,
                                DRAFT),
                )
            else:
                d = int(np.argmax(row))
            props.append(d)
            qrows.append(row)
            t = d
        return props, qrows

    def decode_once(self, eng):
        """The engine's decode phase under speculation: one
        propose+verify round per active row (a verify is a bounded-K
        prefill from the scheduler's point of view — chunked-prefill
        ITL bounds hold with chunk length k+1)."""
        for slot in range(eng.max_batch_size):
            if eng._seqs[slot] is not None:
                self._round(eng, slot)

    def _round(self, eng, slot):
        seq = eng._seqs[slot]
        h = seq.handle
        req = h.request
        pos = seq.pos
        remaining = req.max_new_tokens - seq.emitted
        k_eff = min(self.k, remaining - 1)
        # backend capacity: the verify writes KV at [pos, pos+k_eff] —
        # the paged engine demand-claims transient pages here and may
        # clamp (k_eff 0 degenerates to a one-token verify, the exact
        # vanilla-equivalent step)
        k_eff = max(0, eng._spec_reserve(slot, pos + k_eff) - pos)
        t0 = eng.clock()
        props, qrows = ([], [])
        if k_eff:
            props, qrows = self._propose(eng, slot, seq, pos, k_eff)
        k1 = k_eff + 1
        ids = np.zeros((1, k1), np.int32)
        ids[0, 0] = seq.last_tok
        if k_eff:
            ids[0, 1:] = props
        vsp = None if h.trace is None else get_tracer().start_span(
            "engine.verify", h.trace, slot=slot, pos=pos,
        )
        flat_block, width = eng._spec_gather(slot, pos + k_eff)
        with profiler.RecordEvent(f"serving::spec_verify_w{width}"):
            logits, new_block = eng._run(
                ("spec_verify", width, k1), self._verify_fn(width, k1),
                eng._params, eng._buffers, jnp.asarray(ids), flat_block,
                jnp.int32(pos),
            )
        eng._spec_adopt(slot, new_block, width, pos)
        rows = np.asarray(logits, np.float32)
        if eng.do_sample:
            a, out = accept_sampled(
                rows, qrows, props, jnp.asarray(seq.key), pos,
                eng.temperature, eng.top_k, eng.top_p,
            )
        else:
            a, out = accept_greedy(rows, props)
        dt = eng.clock() - t0
        # bookkeeping BEFORE emission: _append may finish the request
        # (EOS / max_new) and retire the row under us
        self.rounds += 1
        self.proposed += k_eff
        self.accepted += a
        self.emitted += len(out)
        h.spec_rounds = getattr(h, "spec_rounds", 0) + 1
        h.spec_emitted = getattr(h, "spec_emitted", 0) + len(out)
        tid = None if h.trace is None else h.trace.trace_id
        m = eng.metrics
        m.spec_rounds.inc()
        m.spec_proposed.inc(k_eff)
        m.spec_accepted.inc(a)
        m.spec_accept_length.observe(len(out), trace_id=tid)
        # per-SLO-class child bound at admission (zero label work here)
        (seq.slo_itl or m.itl).observe(dt / len(out))
        if vsp is not None:
            vsp.finish(proposed=k_eff, accepted=a, emitted=len(out))
        for t in out:
            if eng._seqs[slot] is None:
                break  # EOS mid-burst: later tokens never happened
            eng._append(slot, int(t))
        if eng._seqs[slot] is not None:
            new_pos = eng._seqs[slot].pos
            # rejected-tail rollback: transient pages past the accepted
            # span go back to the pool; the draft rewinds to the
            # accepted prefix (its rejected-tail KV is masked until
            # overwritten next round)
            eng._spec_rollback(slot, new_pos)
            st = self._slots.get(slot)
            if st is not None and st.fed > new_pos:
                st.fed = new_pos
