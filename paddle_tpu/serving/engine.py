"""Continuous-batching LLM serving engine.

The TPU-first serving shape (cf. the kernel-fusion serving stacks in
PAPERS.md): keep the device running ONE compiled fixed-shape decode-step
program over a resident KV slab, and do all request lifecycle work —
admission, retirement, deadlines, metrics — in a host-side loop between
steps. Three compiled programs total:

- **prefill** (one per power-of-two prompt bucket): runs a right-padded
  prompt through the cache path and emits the first token. Bucketing
  bounds compile count at O(log S_max); padding is numerically exact
  because pad positions only ever write cache slots that decode
  overwrites before the mask exposes them.
- **adopt** (one per bucket): copies a prefill block into a free row of
  the decode slab (``dynamic_update_slice`` at a traced slot index — no
  per-slot recompiles).
- **decode step** (exactly one): ``[max_batch]`` tokens at per-row
  positions -> next tokens. Every row sits at its own depth — this is
  what the vector-``pos`` cache path in ``models.llama`` exists for.
  Free rows ride along as masked garbage (their writes land on slots
  the next adoption overwrites), so admission and retirement NEVER
  trigger a recompile or stall in-flight sequences.

Token streams are exact-equal to ``net.generate`` (same cache dtype):
the per-row program computes the same attention over the same masked
cache, so continuous batching is a scheduling optimization, not an
accuracy trade. The tier-1 serving test pins this token-for-token.
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..models.generation import (
    DEFAULT_CACHE_DTYPE,
    _select_next,
    alloc_kv_caches,
    decode_step,
    prefill,
)
from ..observability.tracing import get_tracer
from .kv_pool import KVCachePool
from .metrics import ServingMetrics
from .sampling_keys import SamplingKeySource
from .scheduler import (
    CANCELLED,
    DONE,
    REASON_ENGINE_CLOSED,
    REASON_SHAPE_MISMATCH,
    REASON_TIMEOUT,
    REASON_TOO_LONG,
    REJECTED,
    RUNNING,
    TIMEOUT,
    RejectedError,
    Request,
    RequestHandle,
    Scheduler,
)


def _flatten(caches):
    return [a for kv in caches for a in kv]


def _unflatten(flat):
    return [(flat[2 * i], flat[2 * i + 1]) for i in range(len(flat) // 2)]


def build_prefill_body(net, do_sample, top_k, top_p):
    """The (un-jitted) bucketed-prefill program body every prefill site
    shares: the engines' per-bucket programs and the fleet tier's
    remote :class:`~.fleet.kv_transfer.PrefillWorker` trace the SAME
    function, which is what makes a disaggregated prefill bit-identical
    to a local one (same weights -> same block, same first token)."""

    def body(params, buffers, ids, length, flat_block, temperature, key):
        net.load_functional_state(params, buffers)
        net.eval()
        logits, caches = prefill(
            net, ids, _unflatten(flat_block), length=length
        )
        if do_sample:
            # position-addressed randomness (sampling_keys): the first
            # sampled token lands at cache position `length`
            key = jax.random.fold_in(key, length)
        nxt = _select_next(logits, do_sample, temperature, top_k, top_p,
                           key)
        return nxt, _flatten(caches)

    return body


def build_chunk_prefill_body(net, do_sample, top_k, top_p):
    """The CHUNKED prefill body (prefix-cache warm path): run only the
    uncached tail of a prompt — ``ids`` [1, tail_bucket] starting at
    cache position ``pos`` over a block whose [0, pos) slots were
    gathered from shared prefix pages. Same sampling head as the full
    program; the logits row is ``length - 1`` relative to the chunk.
    Tier-1-pinned bitwise-equal to the full-prompt prefill body."""

    def body(params, buffers, ids, length, pos, flat_block, temperature,
             key):
        net.load_functional_state(params, buffers)
        net.eval()
        logits, caches = prefill(
            net, ids, _unflatten(flat_block), length=length, pos=pos
        )
        if do_sample:
            # same address as the cold path: the sampled token's cache
            # position is pos + length — warm stays bitwise-equal
            key = jax.random.fold_in(key, pos + length)
        nxt = _select_next(logits, do_sample, temperature, top_k, top_p,
                           key)
        return nxt, _flatten(caches)

    return body


class _Seq:
    """Host-side state of one running sequence (one slab row)."""

    __slots__ = ("handle", "last_tok", "emitted", "key",
                 "slo_itl", "slo_e2e")

    def __init__(self, handle, first_tok, key=None, slo_itl=None,
                 slo_e2e=None):
        self.handle = handle
        self.last_tok = first_tok
        self.emitted = 0  # _append counts (prefill's first token too)
        # the request's base PRNG key (sampling_keys derivation) as a
        # host array — decode steps stack the active rows' keys
        self.key = key
        # per-SLO-class bound histogram children, resolved ONCE at
        # admission (observability.slo): the decode hot loop observes
        # straight into them — zero per-token label resolution, the
        # same pinning discipline as the _traced_live gate
        self.slo_itl = slo_itl
        self.slo_e2e = slo_e2e

    @property
    def pos(self):
        # cache position of the token being fed next step: the last
        # emitted token sits at prompt_len + emitted - 1
        return self.handle.request.prompt_len + self.emitted - 1


class ServingEngine:
    """Continuous-batching serving over a Llama-family causal LM.

    ``max_batch_size`` is the decode slab's row count (in-flight cap);
    ``max_seq_len`` the per-row cache capacity (prompt + generated).
    Weights are snapshotted at construction — serving a training net
    does not race updates. Greedy by default; ``do_sample=True`` with
    temperature/top_k/top_p reuses ``generate()``'s sampling head with
    a per-step PRNG fold so streams stay reproducible per ``seed``.
    """

    def __init__(self, net, *, max_batch_size=8, max_seq_len=256,
                 cache_dtype=None, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, seed=0, min_bucket=16,
                 max_queue_size=64, max_tokens_in_flight=None,
                 scheduler=None, metrics=None, pool=None,
                 clock=time.monotonic, recompile_guard_max=None,
                 weights_version=None, reload_template=None,
                 speculative=None, sessions=None):
        cfg = net.config
        self.net = net
        self.config = cfg
        # routing-tier identity: which weights this engine serves.
        # `generation` counts in-place weight swaps (live reload bumps
        # it); `weights_version` names the checkpoint. A fleet router
        # reads both off the replica status JSON.
        self.generation = 0
        self.weights_version = (
            "v0" if weights_version is None else str(weights_version)
        )
        # live reload state: a prepared swap waits here until no
        # request is in flight (admission pauses meanwhile, so every
        # request runs under exactly one weights version)
        self._pending_swap = None
        self.reload_in_progress = False
        self.last_reload_step = None
        self._reload_template = reload_template
        # AOT warmup bookkeeping: programs compiled (or cache-loaded)
        # before first traffic, and how many came from the persistent
        # compile cache (the /healthz `compile_cache_hits` field)
        self._warmed = set()
        self.compile_cache_hits = 0
        # per-program HBM footprint table (memory_lint estimate + XLA
        # memory_analysis where available), filled by warmup() and
        # surfaced as /healthz `memory` + the
        # paddle_serving_program_peak_bytes gauge family
        self.program_memory = {}
        self.max_batch_size = int(max_batch_size)
        self.max_seq_len = int(max_seq_len)
        self.clock = clock
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p) if top_p is not None else 1.0
        self.max_tokens_in_flight = max_tokens_in_flight
        self.pool = pool or KVCachePool(
            cfg, dtype=cache_dtype or DEFAULT_CACHE_DTYPE,
            min_bucket=min_bucket, max_seq_len=self.max_seq_len,
        )
        self.cache_dtype = self.pool.dtype
        self.scheduler = scheduler or Scheduler(
            max_queue_size=max_queue_size, clock=clock
        )
        self.metrics = metrics or ServingMetrics()
        # conversation bookkeeping (serving.sessions.SessionStore):
        # True builds a default store; a caller-built store passes
        # through; None serves request-at-a-time exactly as before
        if sessions is True:
            from .sessions import SessionStore

            sessions = SessionStore(clock=clock)
        # explicit None/False check: an EMPTY store is len()-falsy
        self.sessions = None if sessions in (None, False) else sessions
        # weight snapshot: serving uses these, not live layer attrs
        self._params = {k: p.value for k, p in net.named_parameters()}
        self._buffers = {k: b.value for k, b in net.named_buffers()}
        self._was_training = net.training
        self._init_kv_backend()
        self._seqs = [None] * self.max_batch_size
        self._key = jax.random.PRNGKey(seed)  # warmup example key shape
        self.keys = SamplingKeySource(seed)
        self.step_count = 0
        # donation only helps (and only works) on accelerators; on the
        # CPU CI it would just emit unusable-donation warnings
        accel = any(d.platform != "cpu" for d in jax.devices())
        self._prefill_fns = {}   # bucket -> jitted fn
        self._adopt_fns = {}     # bucket -> jitted fn
        self._spec_gather_fn = None  # lazy (speculative verify only)
        self._decode_fn = jax.jit(
            self._decode_body, donate_argnums=(3,) if accel else ()
        )
        self._donate = accel
        self._traced = set()
        # count of in-flight requests that carry an open decode span —
        # the decode hot path checks this ONE integer and, when zero
        # (tracing off / sampled out), allocates no span machinery
        self._traced_live = 0
        self._closed = False
        # runtime lint guard: the whole engine design exists so that
        # admission/retirement NEVER recompile — if compile caches grow
        # anyway (bucket sprawl, decode shape drift), the guard turns
        # the silent latency spike into a finding + a chrome-trace span
        from ..analysis.trace_guard import TraceGuard

        if recompile_guard_max is None:
            # expected steady state: one prefill + one adopt program per
            # power-of-two bucket, one decode program; anything well
            # past that is a storm. Bucket count comes from the POOL's
            # geometry (a caller-supplied pool may use a different
            # min_bucket/max_seq_len than this engine's defaults).
            import math

            pool_min = getattr(self.pool, "min_bucket", min_bucket)
            pool_max = getattr(self.pool, "max_seq_len", None) \
                or self.max_seq_len
            buckets = 1 + max(
                0, int(math.log2(max(pool_max, 1)))
                - int(math.log2(max(pool_min, 1)))
            )
            recompile_guard_max = max(4, buckets + 2)
        self.trace_guard = TraceGuard(max_compiles=recompile_guard_max)
        self.trace_guard.on_fire(self._on_guard_fire)
        self.trace_guard.watch("serving::decode_step", self._decode_fn)
        # speculative decoding (serving.speculative): when bound, the
        # decode phase runs propose+verify rounds instead of the fused
        # per-token step
        self.speculative = speculative
        if speculative is not None:
            speculative.bind(self)

    def _init_kv_backend(self):
        """Allocate the resident decode KV state — the slab here
        ([N, S_max] rows claimed per request); the paged engine
        overrides with a page arena + per-row page tables."""
        self._flat = _flatten(
            self.pool.alloc_slab_arrays(self.max_batch_size,
                                        self.max_seq_len)
        )
        self._slab = self.pool.register_slab(self.max_batch_size,
                                             self.max_seq_len)

    def _on_guard_fire(self, finding):
        """A recompile storm at runtime: emit a lint-guard span so the
        storm shows in chrome traces instead of only as a latency
        spike, and count it on the engine's metrics."""
        profiler.record_span(
            f"serving::lint_guard::{finding.rule}", 0.0, kind="lint"
        )
        self.metrics.guard_fires.inc(label=finding.graph)

    # ------------------------------------------------- compiled programs
    def _decode_body(self, params, buffers, tok, flat, pos, temperature,
                     key):
        self.net.load_functional_state(params, buffers)
        self.net.eval()
        logits, caches = decode_step(
            self.net, tok[:, None], _unflatten(flat), pos
        )
        if self.do_sample:
            # `key` is [B, 2] — every row carries its request's base
            # key; the token sampled this step lands at pos + 1, so
            # fold per row (the sampling_keys position address)
            key = jax.vmap(jax.random.fold_in)(key, pos + 1)
        nxt = _select_next(logits, self.do_sample, temperature,
                           self.top_k, self.top_p, key)
        return nxt, _flatten(caches)

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        body = build_prefill_body(self.net, self.do_sample, self.top_k,
                                  self.top_p)
        fn = jax.jit(
            body, donate_argnums=(4,) if self._donate else ()
        )
        self._prefill_fns[bucket] = fn
        self.trace_guard.record_compile(
            "serving::prefill", bucket, origin="serving/engine.py"
        )
        return fn

    def _adopt_fn(self, bucket):
        fn = self._adopt_fns.get(bucket)
        if fn is not None:
            return fn

        def body(flat_decode, flat_block, slot):
            from ..quantization.kv import adopt_into_slab

            return [
                adopt_into_slab(d, b, slot)
                for d, b in zip(flat_decode, flat_block)
            ]

        fn = jax.jit(
            body, donate_argnums=(0,) if self._donate else ()
        )
        self._adopt_fns[bucket] = fn
        self.trace_guard.record_compile(
            "serving::adopt", bucket, origin="serving/engine.py"
        )
        return fn

    def _restore_net_state(self):
        """Put the imperative net back in concrete serving state —
        required after ANYTHING that traced a program body (execution
        tracing or ``.lower()``), which swaps tracers into the Layer
        objects, and after a weight swap, so later snapshots/templates
        see what the engine serves."""
        self.net.load_functional_state(self._params, self._buffers)
        if self._was_training:
            self.net.train()
        else:
            self.net.eval()

    def _run(self, trace_key, fn, *args):
        """Invoke a jitted program; after its FIRST trace, restore the
        net's concrete weights/mode (tracing swaps tracers into the
        imperative Layer objects — generate()'s write-back pattern)."""
        out = fn(*args)
        if trace_key not in self._traced:
            self._traced.add(trace_key)
            self._restore_net_state()
        return out

    def _next_key(self):
        """The admitted request's base PRNG key — one per admission,
        derived by position-addressable fold (sampling_keys), NOT a
        mutable split chain: the same workload in the same order gets
        the same keys on every engine geometry."""
        if not self.do_sample:
            # greedy ignores the key entirely (argmax head) — hand the
            # constant placeholder instead of a per-admission derivation
            return self._key
        return self.keys.next_request_key()

    # ------------------------------------------- speculative backend seams
    #
    # speculative.SpeculativeDecoder drives its one-launch verify
    # through these four hooks. The slab backend is trivial — every row
    # permanently owns the full [0, S_max) span, so reserve always
    # succeeds and rollback is free (rejected-tail KV sits behind the
    # position mask until the row's own later writes overwrite it).
    # The paged engine overrides all four with demand-grown pages.

    def _spec_reserve(self, slot, hi):
        """Guarantee backend KV capacity for verify writes up to cache
        position ``hi``; returns the highest position actually held
        (may clamp below ``hi`` under page pressure)."""
        return min(hi, self.max_seq_len - 1)

    def _spec_gather_prog(self):
        """The (jitted, uncompiled) slab gather program — one row of
        the slab materialized as a prefill-layout ``[1, S_max]``
        block. Built lazily so warmup and first-use share one
        program object."""
        fn = self._spec_gather_fn
        if fn is None:
            from ..quantization.kv import slab_row_block

            def body(flat, s):
                return [slab_row_block(a, s) for a in flat]

            fn = self._spec_gather_fn = jax.jit(body)
            self.trace_guard.record_compile(
                "serving::spec_gather", self.max_seq_len,
                origin="serving/engine.py",
            )
        return fn

    def _spec_gather(self, slot, hi):
        """Materialize row ``slot``'s KV as a prefill-layout ``[1, W]``
        block covering positions [0, ``hi``]; returns
        ``(flat_block, W)``."""
        fn = self._spec_gather_prog()
        return fn(self._flat, jnp.int32(slot)), self.max_seq_len

    def _spec_adopt(self, slot, new_block, width, pos):
        """Land a verify-updated block back as row ``slot``'s KV — the
        same adopt program admission uses, at bucket ``width``
        (positions < ``pos`` came back unchanged; [pos, pos+K] carry
        the verify's writes)."""
        self._flat = self._run(
            ("adopt", width), self._adopt_fn(width),
            self._flat, new_block, jnp.int32(slot),
        )

    def _spec_rollback(self, slot, new_pos):
        """Drop verify writes past the accepted span (the row's next
        token feeds at ``new_pos``). Free on the slab; the paged
        engine releases the rejected tail's demand-claimed pages."""

    # ---------------------------------------------------------- requests
    def _too_long(self, req):
        """Reject-at-submit gate: a request no amount of draining could
        ever admit. Subclasses extend it with their backend's own hard
        ceiling (e.g. the whole page arena)."""
        return req.total_tokens > self.max_seq_len or (
            self.max_tokens_in_flight is not None
            and req.total_tokens > self.max_tokens_in_flight
        )

    def submit(self, input_ids, max_new_tokens=32, *, eos_token_id=None,
               priority=0, deadline_s=None, slo_class=None,
               session_id=None, on_token=None, on_event=None):
        """Enqueue one request; always returns a RequestHandle (status
        REJECTED with ``.reason`` set on backpressure — submit never
        blocks and never throws for load reasons).

        ``slo_class`` names the request's SLO traffic class
        (``interactive`` when None; see ``observability.slo``) — it
        labels the TTFT/ITL/E2E histograms this request lands in.
        ``session_id`` marks the request as one turn of a conversation
        (``serving.sessions``): the session store is touched here and
        records the finished turn's full token chain — never affecting
        the token stream itself. ``on_token(tok, handle)`` streams each
        emitted token as the engine produces it; ``on_event(handle)``
        fires exactly once at the terminal transition (including
        submit-time rejects — a stream consumer always gets an
        ending)."""
        req = Request(
            input_ids, max_new_tokens, eos_token_id=eos_token_id,
            priority=priority, deadline_s=deadline_s,
            slo_class=slo_class, session_id=session_id,
        )
        self.metrics.submitted.inc()
        if session_id is not None and self.sessions is not None \
                and not self._closed:
            self.sessions.touch(session_id)
        if self._closed:
            h = RequestHandle(req, on_token=on_token, on_event=on_event)
            h.submit_time = h.finish_time = self.clock()
            h.status = REJECTED
            h.reason = REASON_ENGINE_CLOSED
            self.metrics.rejected.inc(label=REASON_ENGINE_CLOSED)
            h._fire_terminal()
            return h
        if self._too_long(req):
            h = RequestHandle(req, on_token=on_token, on_event=on_event)
            h.submit_time = h.finish_time = self.clock()
            h.status = REJECTED
            h.reason = REASON_TOO_LONG
            self.metrics.rejected.inc(label=REASON_TOO_LONG)
            h._fire_terminal()
            return h
        try:
            return self.scheduler.submit(req, on_token=on_token,
                                         on_event=on_event)
        except RejectedError as e:
            self.metrics.rejected.inc(label=e.reason)
            return e.handle

    # --------------------------------------------------------- the loop
    @property
    def active_slots(self):
        return sum(1 for s in self._seqs if s is not None)

    def _tokens_in_flight(self):
        return sum(
            s.handle.request.total_tokens
            for s in self._seqs if s is not None
        )

    def _release_slot(self, slot):
        """Return slot ``slot``'s KV residency to the pool (slab row
        here; row + claimed pages in the paged engine)."""
        if self.speculative is not None:
            self.speculative.reset_slot(slot)
        self._slab.release(slot)

    def _finish(self, slot, status, reason=None):
        seq = self._seqs[slot]
        h = seq.handle
        now = self.clock()
        h.status = status
        h.reason = reason
        h.finish_time = now
        h.finished_step = self.step_count
        if status == DONE:
            self.metrics.completed.inc()
        elif status == TIMEOUT:
            self.metrics.timeouts.inc()
        tid = None if h.trace is None else h.trace.trace_id
        (seq.slo_e2e or self.metrics.e2e).observe(
            now - h.submit_time, trace_id=tid
        )
        sp = h._decode_span
        if sp is not None:
            h._decode_span = None
            self._traced_live -= 1
            sp.finish(status=status, tokens=len(h.tokens),
                      **({"error": reason} if reason else {}))
        sid = h.request.session_id
        if sid is not None and self.sessions is not None \
                and not self._closed:
            # the finished turn's FULL conversation ids (prompt +
            # answer) — the exact chain turn N+1's prompt extends
            self.sessions.note_turn(sid, h.output_ids)
        self._seqs[slot] = None
        self._release_slot(slot)
        h._fire_terminal()

    def _append(self, slot, tok):
        seq = self._seqs[slot]
        h = seq.handle
        h.tokens.append(int(tok))
        seq.last_tok = int(tok)
        seq.emitted += 1
        self.metrics.tokens_out.inc()
        h._fire_token(tok)
        req = h.request
        if req.eos_token_id is not None and int(tok) == req.eos_token_id:
            self._finish(slot, DONE)
        elif seq.emitted >= req.max_new_tokens:
            self._finish(slot, DONE)

    def _trace_admitted(self, handle, slot, wait):
        """Admission-time spans under the request's trace context: the
        scheduler-measured queue wait rendered retroactively (the span
        duration IS ``wait`` — the same number the ``queue_wait``
        histogram observed), and the ONE open decode span whose bounded
        event ring the step loop feeds. Zero allocations when the
        request is sampled out (``handle.trace is None``)."""
        tspan = handle.trace
        if tspan is None:
            return
        tr = get_tracer()
        tr.record_span("engine.queue_wait", tspan, wait)
        handle._decode_span = tr.start_span("engine.decode", tspan,
                                            slot=slot)
        if handle._decode_span is not None:
            self._traced_live += 1

    def _admit_one(self, handle):
        req = handle.request
        now = self.clock()
        bucket = self.pool.bucket_for(req.prompt_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, : req.prompt_len] = req.input_ids
        blk = self.pool.alloc(req.prompt_len)
        # claim the slot LAST, with a release guard: an exception out of
        # admission must never strand a claimed slot (a 1-slot engine
        # would wedge forever)
        slot = self._slab.claim()
        assert slot is not None  # caller checked free_slots
        key = self._next_key()
        psp = None if handle.trace is None else get_tracer().start_span(
            "engine.prefill", handle.trace, mode="local", bucket=bucket
        )
        try:
            with profiler.RecordEvent(f"serving::prefill_b{bucket}"):
                nxt, new_flat = self._run(
                    ("prefill", bucket), self._prefill_fn(bucket),
                    self._params, self._buffers, jnp.asarray(ids),
                    jnp.int32(req.prompt_len), _flatten(blk.caches),
                    jnp.float32(self.temperature), key,
                )
                blk.caches = _unflatten(new_flat)
                self._flat = self._run(
                    ("adopt", bucket), self._adopt_fn(bucket),
                    self._flat, new_flat, jnp.int32(slot),
                )
                t0 = int(np.asarray(nxt)[0])
        except BaseException:
            if psp is not None:
                psp.finish(error="admission_error")
            self._slab.release(slot)
            # under donation the failed call may already have consumed
            # the block's buffers — recycling them would poison the
            # bucket's freelist; drop the block instead
            if self._donate:
                self.pool.discard(blk)
            else:
                self.pool.free(blk)
            raise
        if psp is not None:
            psp.finish()
        self.pool.free(blk)
        handle.status = RUNNING
        handle.weights_version = self.weights_version
        handle.admit_time = now
        handle.admitted_step = self.step_count
        handle.first_token_time = self.clock()
        wait = now - handle.submit_time
        tid = None if handle.trace is None else handle.trace.trace_id
        self.metrics.admitted.inc()
        self.metrics.prefill_tokens.inc(req.prompt_len)
        self.metrics.queue_wait.observe(wait, trace_id=tid)
        slo_ttft, slo_itl, slo_e2e = self.metrics.slo_children(
            req.slo_class
        )
        slo_ttft.observe(handle.first_token_time - handle.submit_time,
                         trace_id=tid)
        self._trace_admitted(handle, slot, wait)
        self._seqs[slot] = _Seq(handle, t0, key=np.asarray(key),
                                slo_itl=slo_itl, slo_e2e=slo_e2e)
        self._append(slot, t0)

    def _decode_extra(self):
        """Extra positional decode-step inputs between the KV state and
        ``pos`` (the paged engine passes its page tables here)."""
        return ()

    def _has_capacity(self):
        return self._slab.free_slots > 0

    def _admission_budget(self):
        """Token budget the next admission must fit (None = no cap).
        The paged engine folds free-page capacity in here too."""
        if self.max_tokens_in_flight is None:
            return None
        return self.max_tokens_in_flight - self._tokens_in_flight()

    def _max_admissions_per_step(self):
        """Prefills allowed per engine step. Unbounded for the slab
        engine (its historical behavior); the paged engine caps it —
        the prefill/decode disaggregation lever."""
        return None

    def _admission_fits(self):
        """Optional per-request feasibility predicate handed to the
        scheduler's pop (None = budget-only admission). The prefix-
        caching paged engine supplies one: a warm request's page need
        depends on how much of its prompt the cache covers, which a
        scalar token budget cannot express."""
        return None

    def step(self):
        """One engine iteration: retire expired, admit into free slots,
        run one decode step over the whole resident KV state."""
        if self._closed:
            raise RuntimeError(f"{type(self).__name__} is closed")
        # a staged weight swap applies the moment nothing is in flight
        self._maybe_apply_reload()
        now = self.clock()
        # running sequences past their deadline free their slot NOW
        for i, seq in enumerate(self._seqs):
            if seq is None:
                continue
            dl = self.scheduler.deadline_of(seq.handle)
            if dl is not None and now > dl:
                self._finish(i, TIMEOUT, reason=REASON_TIMEOUT)
        # queued requests whose deadline passed never run at all
        self.scheduler.sweep_expired()
        # admission: fill free capacity in priority-FIFO order under the
        # in-flight token cap (and the per-step prefill cap, when set)
        cap = self._max_admissions_per_step()
        admitted = 0
        # a pending reload pauses admission: in-flight requests drain
        # on the OLD weights, queued ones wait for the swap — zero
        # dropped, one weights version per request
        while self._pending_swap is None and self._has_capacity() and (
            cap is None or admitted < cap
        ):
            handle = self.scheduler.pop_next(self._admission_budget(),
                                             fits=self._admission_fits())
            if handle is None:
                break
            try:
                self._admit_one(handle)
            except BaseException as e:
                # the handle was already popped — resolve it before
                # propagating, or a caller polling h.finished waits
                # forever on a request no queue holds anymore
                handle.status = REJECTED
                handle.reason = f"admission_error:{type(e).__name__}"
                handle.finish_time = self.clock()
                self.metrics.rejected.inc(label="admission_error")
                handle._fire_terminal()
                raise
            admitted += 1
        # single metrics channel for queued-expiry, whether the sweep or
        # a lazy pop_next expired the request (a deadline can pass
        # mid-step while a prefill compiles)
        for _ in self.scheduler.drain_timed_out():
            self.metrics.timeouts.inc()
        self._decode_once()
        # the last in-flight request may have finished this step — a
        # pending swap must not wait for another external step() call
        self._maybe_apply_reload()
        self.step_count += 1
        # poll jit-internal compile caches (decode shape drift is
        # invisible to the bucket maps above); fires _on_guard_fire
        self.trace_guard.check()
        self.metrics.observe_step(self.scheduler.depth, self.active_slots)

    def _decode_once(self):
        """One fused decode step over every row (free rows are masked
        garbage; their writes land on slots adoption overwrites)."""
        active = [i for i, s in enumerate(self._seqs) if s is not None]
        if not active:
            return
        if self.speculative is not None:
            # propose + one-launch verify per row instead of the fused
            # per-token step (speculative.py)
            self.speculative.decode_once(self)
            return
        tok = np.zeros((self.max_batch_size,), np.int32)
        pos = np.zeros((self.max_batch_size,), np.int32)
        keys = np.zeros((self.max_batch_size, 2), np.uint32)
        for i in active:
            tok[i] = self._seqs[i].last_tok
            pos[i] = self._seqs[i].pos
            keys[i] = self._seqs[i].key
        t0 = self.clock()
        with profiler.RecordEvent("serving::decode_step"):
            nxt, self._flat = self._run(
                ("decode",), self._decode_fn,
                self._params, self._buffers, jnp.asarray(tok),
                self._flat, *self._decode_extra(), jnp.asarray(pos),
                jnp.float32(self.temperature), jnp.asarray(keys),
            )
            nxt = np.asarray(nxt)
        dt = self.clock() - t0
        if self._traced_live:
            # ONE bounded-ring event per traced request per step (the
            # O(1)-spans discipline: a 500-step decode stays one span);
            # sampled-out runs never reach this branch — the single
            # integer check above is the whole hot-path cost
            occ = len(active)
            for i in active:
                sp = self._seqs[i].handle._decode_span
                if sp is not None:
                    sp.event("decode_step", step=self.step_count,
                             occupancy=occ, dt_s=dt)
        for i in active:
            seq = self._seqs[i]
            if seq is None:
                continue  # finished by an earlier row this step
            # per-class child bound at admission: no label resolution
            # (and no allocation) on this per-token path
            (seq.slo_itl or self.metrics.itl).observe(dt)
            self._append(i, nxt[i])

    def run_until_idle(self, max_steps=100_000):
        """Drive ``step()`` until queue and slab are empty."""
        steps = 0
        while self.scheduler.depth or self.active_slots:
            if steps >= max_steps:
                raise RuntimeError(
                    f"run_until_idle: not drained after {max_steps} steps"
                    f" (queue={self.scheduler.depth},"
                    f" active={self.active_slots})"
                )
            self.step()
            steps += 1
        return steps

    def generate(self, prompts, max_new_tokens=32, **submit_kwargs):
        """Batch convenience: submit every prompt, drain, and return
        the handles in submit order."""
        handles = [
            self.submit(p, max_new_tokens, **submit_kwargs)
            for p in prompts
        ]
        self.run_until_idle()
        return handles

    # ------------------------------------------------------- live reload
    def prepare_reload(self, ckpt_dir, *, weights_version=None,
                       template_net=None, verify_level="full"):
        """Stage a weight swap from a committed checkpoint directory
        (or a checkpoint root — newest committed step wins): verify the
        manifest/CRCs, load into a template, quantize for serving when
        this engine runs quantized weights, and validate against the
        compiled programs' snapshot. Pure and thread-safe — run it OFF
        the step loop; pass the result to :meth:`commit_reload`.
        Failures come back as a non-ok :class:`~.reload.StagedReload`
        (counted by outcome), never an exception — the engine keeps
        serving the last committed weights."""
        from .reload import prepare_state_swap

        staged = prepare_state_swap(
            self.net, self._params, self._buffers, ckpt_dir,
            weights_version=weights_version,
            template_net=template_net or self._reload_template,
            verify_level=verify_level,
        )
        if not staged.ok:
            self.metrics.reloads.inc(label=staged.outcome)
        return staged

    def commit_reload(self, staged):
        """Hand a prepared swap to the step loop (same single-thread
        discipline as :meth:`step` — the HTTP frontend calls this under
        its driver lock). Applies immediately when nothing is in
        flight; otherwise admission pauses and the swap lands at the
        first step boundary with zero active requests. A staged swap
        committed over a still-pending one supersedes it (newest
        checkpoint wins)."""
        if not staged.ok:
            return staged
        if self._closed:
            staged.ok = False
            staged.outcome = "engine_closed"
            self.metrics.reloads.inc(label="engine_closed")
            return staged
        if self._pending_swap is not None:
            self.metrics.reloads.inc(label="superseded")
        staged.staged_at = self.clock()
        self._pending_swap = staged
        self.reload_in_progress = True
        self._maybe_apply_reload()
        return staged

    def reload_weights(self, ckpt_dir, **kw):
        """prepare + commit in one call (callers on the engine's own
        thread — tests, benches, the launch entrypoint)."""
        return self.commit_reload(self.prepare_reload(ckpt_dir, **kw))

    def _maybe_apply_reload(self):
        if self._pending_swap is not None and self.active_slots == 0:
            self._apply_reload()

    def _apply_reload(self):
        from . import chaos as _chaos

        staged = self._pending_swap
        try:
            # the deterministic kill-mid-swap seam: a fault here must
            # leave the engine fully on the OLD weights (nothing below
            # has mutated yet — the swap is all-or-nothing)
            _chaos.poke("reload.apply", step=staged.step,
                        version=staged.weights_version)
        except BaseException as e:
            self._pending_swap = None
            self.reload_in_progress = False
            staged.ok = False
            staged.outcome = "error"
            staged.error = repr(e)
            self.metrics.reloads.inc(label="error")
            return
        self._params = staged.params
        self._buffers = staged.buffers
        self.weights_version = staged.weights_version
        self.generation += 1
        self.last_reload_step = staged.step
        self._pending_swap = None
        self.reload_in_progress = False
        self._restore_net_state()
        # backend hook: the paged engine flushes its prefix cache here —
        # a post-swap request must never adopt KV computed under the
        # weights that just rotated out
        self._on_weights_swapped()
        # disaggregation stays exact across the rotation: the prefill
        # worker's version-skew refusal now rejects OLD-weights blocks
        tr = getattr(self, "prefill_transport", None)
        if tr is not None and getattr(tr, "expected_weights_version",
                                      None) is not None:
            tr.expected_weights_version = staged.weights_version
        if staged.staged_at is not None:
            pause = self.clock() - staged.staged_at
            self.metrics.reload_ttft_spike.observe(pause)
            # the admission-pause window as a (head-sampled) root span:
            # the reload's worst-case extra TTFT is visible in the same
            # timeline as the requests it delayed
            get_tracer().record_trace(
                "engine.reload_pause", pause,
                version=staged.weights_version, step=staged.step,
            )
        self.metrics.reloads.inc(label="ok")
        staged.outcome = "applied"
        try:
            from ..observability import get_flight_recorder

            get_flight_recorder().note(
                "weights_reload", step=staged.step,
                version=staged.weights_version, path=staged.path,
                generation=self.generation,
            )
        except Exception:
            pass

    def _on_weights_swapped(self):
        """Post-swap hook, called with the new weights installed and
        nothing in flight. The paged engine flushes its prefix cache
        here (and calls up); speculation re-snapshots the self-spec
        draft and invalidates old-weights draft caches."""
        if self.speculative is not None:
            self.speculative.on_weights_swapped(self)

    # ------------------------------------------------------- AOT warmup
    def _warmup_buckets(self):
        """Every prompt bucket this engine can compile (the same
        power-of-two ladder the pool admits)."""
        mx = getattr(self.pool, "max_seq_len", None) or self.max_seq_len
        out, L = [], getattr(self.pool, "min_bucket", 16)
        while True:
            b = self.pool.bucket_for(min(L, mx))
            if b not in out:
                out.append(b)
            if L >= mx:
                return out
            L *= 2

    def _decode_example_args(self):
        B = self.max_batch_size
        return (
            self._params, self._buffers, jnp.zeros((B,), jnp.int32),
            self._flat, *self._decode_extra(),
            jnp.zeros((B,), jnp.int32),
            jnp.float32(self.temperature),
            jnp.zeros((B, 2), jnp.uint32),
        )

    def _adopt_example_args(self, flat_block, bucket):
        return (self._flat, flat_block, jnp.int32(0))

    def _program_signature(self, name):
        cfg = self.config
        sig = {
            "program": name,
            "engine": type(self).__name__,
            "max_batch": self.max_batch_size,
            "max_seq": self.max_seq_len,
            "cache_dtype": str(self.cache_dtype),
            "do_sample": self.do_sample,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "donate": self._donate,
            "model": {
                "vocab": int(cfg.vocab_size),
                "hidden": int(cfg.hidden_size),
                "inter": int(cfg.intermediate_size),
                "layers": int(cfg.num_hidden_layers),
                "heads": int(cfg.num_attention_heads),
                "kv_heads": int(cfg.kv_heads),
            },
        }
        if name.startswith("spec_") and self.speculative is not None:
            # draft geometry/acceptance depth change the traced program
            # — a cache hit across different speculative configs would
            # install the wrong executable
            sig["speculative"] = self.speculative.signature()
        return sig

    def _verify_widths(self, buckets):
        """Block widths the speculative verify can see. The slab
        gathers every row at full width; the paged engine overrides
        with its bucket ladder."""
        return [self.max_seq_len]

    def _warm_spec_gather(self, cache, stats, buckets):
        """Pre-compile the KV-gather program(s) the speculative round
        issues before every verify. Slab: one full-width row gather."""
        self._warm_one(
            cache, "spec_gather", ("spec_gather",),
            self._spec_gather_prog(),
            (self._flat, jnp.int32(0)),
            lambda comp: setattr(self, "_spec_gather_fn", comp), stats,
        )

    def _warm_one(self, cache, name, trace_key, jitfn, args, install,
                  stats, donate=()):
        if trace_key in self._warmed:
            return  # idempotent: the installed executable stands
        stats["programs"] += 1
        key = meta = None
        if cache is not None:
            key, meta = cache.key_for(self._program_signature(name),
                                      args)
            comp = cache.load(key)
            if comp is not None:
                install(comp)
                self._warmed.add(trace_key)
                self.compile_cache_hits += 1
                stats["aot_hits"] += 1
                self._memory_note(name, jitfn, args, donate, comp)
                return
        comp = jitfn.lower(*args).compile()
        install(comp)
        self._warmed.add(trace_key)
        if cache is not None and cache.save(key, comp, meta):
            stats["aot_saves"] += 1
        self._memory_note(name, jitfn, args, donate, comp)

    def _memory_note(self, name, fn, args, donate, comp):
        """Record one warmed program's HBM footprint: the live-range
        estimate (memory_lint, with THIS process's actual donation) and
        the compiled executable's own ``memory_analysis()`` where the
        backend exposes it, drift already judged. Analysis can never
        fail a warmup."""
        try:
            from .. import analysis

            est = analysis.estimate_fn(
                fn, *args, graph=name, donate_argnums=donate,
            )
            entry = est.to_dict()
            stats = analysis.xla_memory_stats(comp)
            if stats is not None:
                entry["xla"] = stats
                drift = analysis.drift_finding(est, stats)
                entry["drift"] = None if drift is None else drift.message
            self.program_memory[name] = entry
        except Exception:
            pass

    def memory_report(self):
        """The per-program footprint table warmup() filled — the
        /healthz ``memory`` block and serve_bench's ``memory``
        record. None before warmup."""
        if not self.program_memory:
            return None
        return {
            "programs": dict(self.program_memory),
            "max_peak_bytes": max(
                e["peak_bytes"] for e in self.program_memory.values()
            ),
        }

    def _publish_memory_gauges(self):
        try:
            from ..observability import get_registry

            g = get_registry().gauge(
                "paddle_serving_program_peak_bytes",
                help="estimated peak resident bytes per compiled "
                     "serving program (memory_lint live-range model)",
                unit="bytes",
            )
            for name, entry in self.program_memory.items():
                g.set(float(entry["peak_bytes"]), program=name)
        except Exception:
            pass

    def warmup(self, aot_cache=None, buckets=None):
        """Compile every fixed-shape program — the decode step plus
        prefill and adopt per prompt bucket — BEFORE first traffic, so
        a fresh replica reaches READY with its full compiled inventory
        and the first request pays sockets, not XLA.

        With ``aot_cache`` (an ``jit.aot_cache.AOTProgramCache`` or a
        directory path), finished executables are serialized there and
        a relaunched engine with the same geometry loads them instead
        of tracing or compiling ANYTHING — ``compile_cache_hits``
        counts the loads, and the trace-guard inventory stays flat at
        first traffic (the reload-smoke acceptance pin). Returns
        ``{"programs", "aot_hits", "aot_saves"}``."""
        if self._closed:
            raise RuntimeError(f"{type(self).__name__} is closed")
        from ..jit import aot_cache as aot_mod

        cache = aot_mod.resolve(aot_cache)
        if buckets is None:
            buckets = self._warmup_buckets()
        stats = {"programs": 0, "aot_hits": 0, "aot_saves": 0}
        try:
            decode_fresh = ("decode",) not in self._warmed
            self._warm_one(
                cache, "decode", ("decode",), self._decode_fn,
                self._decode_example_args(),
                lambda comp: setattr(self, "_decode_fn", comp), stats,
                donate=(3,) if self._donate else (),
            )
            if decode_fresh:
                self.trace_guard.record_compile(
                    "serving::decode_step", "warmup", origin="warmup"
                )
            for b in buckets:
                blk = self.pool.alloc(b)
                try:
                    flat = _flatten(blk.caches)
                    pargs = (
                        self._params, self._buffers,
                        jnp.zeros((1, b), jnp.int32), jnp.int32(b),
                        flat, jnp.float32(self.temperature), self._key,
                    )
                    self._warm_one(
                        cache, f"prefill_b{b}", ("prefill", b),
                        self._prefill_fn(b), pargs,
                        lambda comp, b=b: self._prefill_fns
                        .__setitem__(b, comp), stats,
                        donate=(4,) if self._donate else (),
                    )
                    self._warm_one(
                        cache, f"adopt_b{b}", ("adopt", b),
                        self._adopt_fn(b),
                        self._adopt_example_args(flat, b),
                        lambda comp, b=b: self._adopt_fns
                        .__setitem__(b, comp), stats,
                        donate=(0,) if self._donate else (),
                    )
                finally:
                    self.pool.free(blk)
            if self.speculative is not None:
                # PR 16 residual: the speculative inventory (draft
                # prefill/decode, steady-state verify, gather) warms
                # and AOT-persists with everything else — the first
                # speculative round pays zero compiles
                self.speculative.warmup(self, cache, stats, buckets)
        finally:
            # lowering traces the program bodies — skipping the
            # restore leaks tracers into any LATER snapshot of the net
            self._restore_net_state()
        self._publish_memory_gauges()
        return stats

    def close(self):
        """Shut the engine down: cancel queued AND in-flight requests
        (their handles finish with status CANCELLED, partial tokens
        kept), release every slab slot so pool occupancy returns to 0,
        and drop all compiled programs."""
        self._closed = True
        if self._pending_swap is not None:
            self._pending_swap = None
            self.reload_in_progress = False
            self.metrics.reloads.inc(label="abandoned")
        while True:
            h = self.scheduler.pop_next()
            if h is None:
                break
            h.status = CANCELLED
            h.reason = REASON_ENGINE_CLOSED
            h.finish_time = self.clock()
            h._fire_terminal()
        for _ in self.scheduler.drain_timed_out():
            self.metrics.timeouts.inc()
        for i, seq in enumerate(self._seqs):
            if seq is None:
                continue
            h = seq.handle
            h.status = CANCELLED
            h.reason = REASON_ENGINE_CLOSED
            h.finish_time = self.clock()
            h.finished_step = self.step_count
            self._seqs[i] = None
            self._release_slot(i)
            h._fire_terminal()
        self._flat = None
        self._decode_fn = None
        if self.sessions is not None:
            self.sessions.close()
        # the guard's watch entry holds the jitted callable too — drop
        # it, or close() would keep the compiled program resident
        self.trace_guard.unwatch("serving::decode_step")
        self._prefill_fns.clear()
        self._adopt_fns.clear()
        self._spec_gather_fn = None
        if self.speculative is not None:
            self.speculative.unbind()


class StaticBatchEngine:
    """Serving adapter for SAVED decode artifacts (``jit.save`` ->
    ``inference.create_predictor``). A saved program is one fixed
    [B, S_prompt] whole-decode computation, so continuous batching is
    impossible — but the request/scheduler/metrics surface still
    applies: requests queue with backpressure, run in batches of B
    (short batches padded by repeating the first row), and report the
    same metrics. Built by ``Predictor.into_engine()``."""

    def __init__(self, predictor, *, max_queue_size=64, scheduler=None,
                 metrics=None, clock=time.monotonic, paged=False,
                 page_size=16):
        specs = getattr(predictor, "_input_specs", None)
        if not specs:
            raise ValueError(
                "predictor carries no input specs; into_engine() needs "
                "an artifact saved by paddle_tpu.jit.save"
            )
        shape = specs[0].get("shape") or []
        if len(shape) != 2:
            raise ValueError(
                f"expected a [B, S_prompt] decode artifact, got input "
                f"shape {shape}"
            )
        self.predictor = predictor
        self.batch_size, self.prompt_len = int(shape[0]), int(shape[1])
        self.clock = clock
        self.scheduler = scheduler or Scheduler(
            max_queue_size=max_queue_size, clock=clock
        )
        self.metrics = metrics or ServingMetrics()
        # paged residency accounting: the saved program's internal KV
        # span ([B, S_total]) flows through the same page-pool surface
        # the live paged engine uses (claim while a batch is in flight,
        # zero-leak when idle). The pool is sized on the first run — the
        # artifact only reveals S_total through its output shape.
        self._paged = bool(paged)
        self._page_size = int(page_size)
        self.page_pool = None
        self._total_len = None

    def submit(self, input_ids, *, priority=0, deadline_s=None,
               slo_class=None, on_token=None, on_event=None):
        req = Request(input_ids, 1, priority=priority,
                      deadline_s=deadline_s, slo_class=slo_class)
        self.metrics.submitted.inc()
        if req.prompt_len != self.prompt_len:
            h = RequestHandle(req, on_token=on_token, on_event=on_event)
            h.submit_time = h.finish_time = self.clock()
            h.status = REJECTED
            h.reason = REASON_SHAPE_MISMATCH
            self.metrics.rejected.inc(label=REASON_SHAPE_MISMATCH)
            h._fire_terminal()
            return h
        try:
            return self.scheduler.submit(req, on_token=on_token,
                                         on_event=on_event)
        except RejectedError as e:
            self.metrics.rejected.inc(label=e.reason)
            return e.handle

    def run_until_idle(self):
        name = self.predictor.get_input_names()[0]
        while self.scheduler.depth:
            self.scheduler.sweep_expired()
            for _ in self.scheduler.drain_timed_out():
                self.metrics.timeouts.inc()
            batch = []
            while len(batch) < self.batch_size:
                h = self.scheduler.pop_next()
                if h is None:
                    break
                batch.append(h)
            if not batch:
                continue
            ids = np.stack(
                [batch[i % len(batch)].request.input_ids
                 for i in range(self.batch_size)]
            ).astype(np.int32)
            t0 = self.clock()
            claim = None
            if self._paged and self.page_pool is not None:
                claim = self.page_pool.claim(
                    self.batch_size
                    * self.page_pool.pages_for(self._total_len)
                )
            self.predictor.get_input_handle(name).copy_from_cpu(ids)
            try:
                self.predictor.run()
                out = self.predictor.get_output_handle(
                    self.predictor.get_output_names()[0]
                ).copy_to_cpu()
            finally:
                if claim is not None:
                    self.page_pool.release(claim)
            dt = self.clock() - t0
            now = self.clock()
            new = out.shape[1] - self.prompt_len
            if self._paged and self.page_pool is None:
                # first run revealed S_total: size the pool to the
                # artifact's exact KV span and account this run's claim
                # retroactively (claims/releases counters still tally)
                from .paged_pool import PagedKVPool

                self._total_len = int(out.shape[1])
                pool = PagedKVPool(
                    None, page_size=self._page_size,
                    num_pages=self.batch_size
                    * -(-self._total_len // self._page_size),
                    max_seq_len=self._total_len,
                )
                pool.release(pool.claim(pool.num_pages))
                self.page_pool = pool
            for i, h in enumerate(batch):
                h.tokens = [int(t) for t in out[i, self.prompt_len:]]
                h.status = DONE
                h.admit_time = t0
                h.first_token_time = now
                h.finish_time = now
                self.metrics.admitted.inc()
                self.metrics.completed.inc()
                self.metrics.tokens_out.inc(new)
                self.metrics.prefill_tokens.inc(self.prompt_len)
                self.metrics.queue_wait.observe(t0 - h.submit_time)
                slo_ttft, slo_itl, slo_e2e = self.metrics.slo_children(
                    h.request.slo_class
                )
                slo_ttft.observe(now - h.submit_time)
                if new > 1:
                    slo_itl.observe(dt / new)
                slo_e2e.observe(now - h.submit_time)
                for t in h.tokens:
                    h._fire_token(t)
                h._fire_terminal()
            self.metrics.observe_step(self.scheduler.depth, len(batch))
        for _ in self.scheduler.drain_timed_out():
            self.metrics.timeouts.inc()
