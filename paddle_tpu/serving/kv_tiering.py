"""Hierarchical KV-cache tiering — spill cold prefix pages, don't kill them.

The prefix cache (``prefix_cache.PrefixCache``) makes HBM the only
home a cached page has: under arena pressure a refcount-0 prefix is
evicted outright, and the next turn of that conversation re-prefills
everything the page held. This module adds the tiers below HBM:

- **host tier** — a bounded byte-budget store of spilled pages in
  process RAM. ``PrefixCache.evict`` (with a tier attached) reads the
  victim page's arena bytes and ``put``s them here instead of just
  dropping them — same leaf-first LRU victim order, spill replacing
  outright eviction.
- **disk tier (optional)** — when the host budget overflows, the
  coldest host payloads demote to files under ``disk_dir`` instead of
  being dropped (their CRC rides along; a torn file refuses restore
  exactly like a corrupt RAM payload).

Every spilled page is one CRC-checked frame in the PR 10 wire format
(``fleet.kv_transfer``: ``MAGIC | len | crc32 | header_json | raw
leaf bytes``) — the same encode/decode helpers the disaggregated
prefill path ships KV pages with, so a payload torn by any layer
(RAM corruption, truncated file, version skew) is REFUSED at restore
and the request falls back to cold prefill: tiering is an
optimization, never a correctness dependency. A restore additionally
refuses any payload whose recorded ``weights_version`` differs from
the matching request's — structurally unreachable (chain keys re-root
on rotation and the engine flushes tiers on swap), but checked anyway:
stale-weights KV must never adopt.

The store is driver-thread-only, like the cache that owns it.
"""
from __future__ import annotations

import json
import os
import zlib
from collections import OrderedDict

from ..observability import Gauge, get_flight_recorder
from .fleet.kv_transfer import (
    MAGIC,
    MAX_FRAME_BYTES,
    TransferError,
    _decode_array,
    _encode_array,
    _HEAD,
    _HLEN,
)
from .metrics import Counter

TIER_HOST = "host"
TIER_DISK = "disk"


# ------------------------------------------------------------------ frames
def pack_page(arrays, meta):
    """One spilled page as a self-verifying frame: ``meta`` (a small
    JSON dict — weights_version, valid_len, ...) plus every host array
    of the page, concatenated raw. Same layout as a kv_transfer wire
    frame, so the CRC covers header and payload together."""
    headers, parts = [], []
    for a in arrays:
        h, b = _encode_array(a)
        headers.append(h)
        parts.append(b)
    header = dict(meta)
    header["kind"] = "kv_page"
    header["leaves"] = headers
    hj = json.dumps(header).encode("utf-8")
    payload = _HLEN.pack(len(hj)) + hj + b"".join(parts)
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return MAGIC + _HEAD.pack(len(payload), crc) + payload


def unpack_page(frame):
    """Decode + verify one spilled-page frame -> ``(meta, arrays)``.
    Raises :class:`~.fleet.kv_transfer.TransferError` on ANY damage
    (magic, length, CRC, header, leaf sizes) — the caller counts the
    refusal and falls back to cold prefill."""
    if len(frame) < 4 + _HEAD.size or frame[:4] != MAGIC:
        raise TransferError("bad spilled-page magic")
    length, crc = _HEAD.unpack(frame[4:4 + _HEAD.size])
    payload = frame[4 + _HEAD.size:]
    if length != len(payload) or length > MAX_FRAME_BYTES:
        raise TransferError(
            f"spilled-page length {length} != payload {len(payload)}"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TransferError("spilled-page CRC mismatch")
    hlen = _HLEN.unpack(payload[:_HLEN.size])[0]
    if _HLEN.size + hlen > length:
        raise TransferError("spilled-page header overruns payload")
    try:
        header = json.loads(
            payload[_HLEN.size:_HLEN.size + hlen].decode("utf-8")
        )
    except Exception as e:
        raise TransferError(f"bad spilled-page header: {e!r}")
    blob = payload[_HLEN.size + hlen:]
    arrays, off = [], 0
    import numpy as np

    for h in header.get("leaves", ()):
        import jax.numpy as jnp

        n = int(np.prod(h["shape"])) * jnp.dtype(h["dtype"]).itemsize
        arrays.append(_decode_array(h, blob[off:off + n]))
        off += n
    if off != len(blob):
        raise TransferError(
            f"spilled-page leaves cover {off}B != blob {len(blob)}B"
        )
    meta = {k: v for k, v in header.items()
            if k not in ("kind", "leaves")}
    return meta, arrays


class _Spilled:
    """One spilled page record. ``frame`` holds the bytes while the
    record sits in the host tier; a disk-demoted record holds ``path``
    instead (the frame — CRC included — IS the file content)."""

    __slots__ = ("key", "parent", "tokens", "valid_len",
                 "weights_version", "frame", "path", "nbytes", "tier")

    def __init__(self, key, parent, tokens, valid_len,
                 weights_version, frame):
        self.key = key
        self.parent = parent
        self.tokens = tuple(int(t) for t in tokens)
        self.valid_len = int(valid_len)
        self.weights_version = str(weights_version)
        self.frame = frame
        self.path = None
        self.nbytes = len(frame)
        self.tier = TIER_HOST


class TieredPageStore:
    """Bounded spill store for refcount-0 prefix pages.

    ``put`` admits a packed page under the host byte budget, demoting
    the coldest records to disk (when ``disk_dir`` is set) or dropping
    them (counted — capacity exhaustion degrades to plain eviction,
    never an error). ``get`` returns ``(record, meta, arrays)`` after
    frame verification, or None with the refusal counted. Keys are the
    prefix cache's chain keys, so the parent index supports the same
    partial-tail search ``match`` runs over resident entries."""

    def __init__(self, *, host_budget_bytes=64 << 20, disk_dir=None,
                 disk_budget_bytes=None, registry=None,
                 namespace="paddle_serving", recorder=None):
        self.host_budget_bytes = int(host_budget_bytes)
        self.disk_dir = disk_dir
        self.disk_budget_bytes = (
            None if disk_budget_bytes is None else int(disk_budget_bytes)
        )
        if disk_dir is not None:
            os.makedirs(disk_dir, exist_ok=True)
        self._records = OrderedDict()   # key -> _Spilled, LRU order
        self._children = {}             # parent -> set of keys
        self._bytes = {TIER_HOST: 0, TIER_DISK: 0}
        self._file_seq = 0
        self._rec = recorder if recorder is not None \
            else get_flight_recorder()
        ns = namespace
        self.tier_pages = Gauge(
            "kv_tier_pages", prom_name=f"{ns}_kv_tier_pages",
            help="spilled prefix pages resident per tier")
        self.tier_bytes = Gauge(
            "kv_tier_bytes", prom_name=f"{ns}_kv_tier_bytes",
            help="spilled payload bytes resident per tier")
        self.spills = Counter(
            "kv_tier_spills", labelname="tier",
            prom_name=f"{ns}_kv_tier_spills_total",
            help="prefix pages spilled into a tier (host admit, disk "
                 "demote)")
        self.restores = Counter(
            "kv_tier_restores", labelname="tier",
            prom_name=f"{ns}_kv_tier_restores_total",
            help="spilled pages restored into the HBM arena, by "
                 "source tier")
        self.crc_refused = Counter(
            "kv_tier_crc_refused",
            prom_name=f"{ns}_kv_tier_crc_refused_total",
            help="spilled pages REFUSED at restore: frame damage "
                 "(magic/length/CRC/header) — request falls back to "
                 "cold prefill")
        self.stale_refused = Counter(
            "kv_tier_stale_refused",
            prom_name=f"{ns}_kv_tier_stale_refused_total",
            help="spilled pages REFUSED at restore: weights_version "
                 "mismatch")
        self.dropped = Counter(
            "kv_tier_dropped", labelname="reason",
            prom_name=f"{ns}_kv_tier_dropped_total",
            help="spilled pages dropped without restore (budget "
                 "pressure, flush, damage)")
        if registry is None:
            from ..observability import get_registry

            registry = get_registry()
        registry.register_all([
            self.tier_pages, self.tier_bytes, self.spills,
            self.restores, self.crc_refused, self.stale_refused,
            self.dropped,
        ])
        self._update_gauges()

    # ------------------------------------------------------------ admit
    def put(self, key, parent, tokens, valid_len, arrays,
            weights_version):
        """Spill one page. Returns True when the payload is resident
        somewhere below HBM afterwards; False when it cannot fit (the
        caller proceeds with plain eviction)."""
        frame = pack_page(
            arrays,
            {"weights_version": str(weights_version),
             "valid_len": int(valid_len)},
        )
        old = self._records.pop(key, None)
        if old is not None:
            self._discard(old, count=False)
        rec = _Spilled(key, parent, tokens, valid_len,
                       weights_version, frame)
        # make room: demote (or drop) coldest host records first
        while (self._bytes[TIER_HOST] + rec.nbytes
               > self.host_budget_bytes):
            victim = self._oldest(TIER_HOST)
            if victim is None:
                break
            if not self._demote(victim):
                self._records.pop(victim.key, None)
                self._discard(victim)
        if self._bytes[TIER_HOST] + rec.nbytes <= self.host_budget_bytes:
            self._records[key] = rec
            self._children.setdefault(parent, set()).add(key)
            self._bytes[TIER_HOST] += rec.nbytes
            self.spills.inc(label=TIER_HOST)
            self._rec.note("kv_spill", tier=TIER_HOST, bytes=rec.nbytes,
                           tokens=len(rec.tokens))
            self._update_gauges()
            return True
        # host cannot hold it even after demotions (payload bigger
        # than the whole budget, or everything resident is disk-bound
        # already): spill straight to disk when one is attached
        self._records[key] = rec
        self._children.setdefault(parent, set()).add(key)
        self._bytes[TIER_HOST] += rec.nbytes  # _demote rebalances
        if self._demote(rec):
            self._update_gauges()
            return True
        self._bytes[TIER_HOST] -= rec.nbytes
        self._records.pop(key, None)
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(key)
            if not kids:
                self._children.pop(parent, None)
        self.dropped.inc(label="budget")
        self._update_gauges()
        return False

    def _disk_ok(self, nbytes):
        if self.disk_dir is None:
            return False
        return (self.disk_budget_bytes is None
                or self._bytes[TIER_DISK] + nbytes
                <= self.disk_budget_bytes)

    def _oldest(self, tier):
        for rec in self._records.values():
            if rec.tier == tier:
                return rec
        return None

    def _demote(self, rec):
        """Move one host record's payload to a file. False when disk
        is absent/over budget (the caller drops the record instead)."""
        if not self._disk_ok(rec.nbytes):
            return False
        self._file_seq += 1
        path = os.path.join(self.disk_dir,
                            f"kvpage-{self._file_seq:08d}.pkv")
        try:
            with open(path, "wb") as f:
                f.write(rec.frame)
        except OSError:
            return False
        self._bytes[TIER_HOST] -= rec.nbytes
        self._bytes[TIER_DISK] += rec.nbytes
        rec.frame = None
        rec.path = path
        rec.tier = TIER_DISK
        self.spills.inc(label=TIER_DISK)
        self._rec.note("kv_demote", tier=TIER_DISK, bytes=rec.nbytes)
        # keep LRU position: a demotion is not a touch
        return True

    # ------------------------------------------------------------ lookup
    def children(self, parent):
        """Spilled chain keys under ``parent`` — the tail-search hook
        ``PrefixCache.match`` uses alongside its resident children."""
        return tuple(self._children.get(parent, ()))

    def peek(self, key):
        return self._records.get(key)

    def iter_records(self):
        """Resident spill records, coldest first (insertion/LRU
        order). Read-only bookkeeping surface — the capacity sweep in
        ``tools/serve_bench.py --multi-turn`` replays the store's own
        keep-newest policy over these at simulated budgets."""
        return tuple(self._records.values())

    def get(self, key, weights_version=None):
        """Fetch + verify one spilled page: ``(record, meta, arrays)``
        or None (absent / stale / damaged — refusals counted, the
        record dropped; the caller cold-prefills). Does NOT remove a
        healthy record — the caller pops it after the restore lands."""
        rec = self._records.get(key)
        if rec is None:
            return None
        if weights_version is not None \
                and rec.weights_version != str(weights_version):
            self.stale_refused.inc()
            self._rec.note("kv_restore_refused", reason="stale_weights")
            self._records.pop(key, None)
            self._discard(rec)
            self._update_gauges()
            return None
        frame = rec.frame
        if frame is None and rec.path is not None:
            try:
                with open(rec.path, "rb") as f:
                    frame = f.read()
            except OSError:
                frame = b""
        try:
            meta, arrays = unpack_page(frame)
        except TransferError:
            self.crc_refused.inc()
            self._rec.note("kv_restore_refused", reason="frame_damage",
                           tier=rec.tier)
            self._records.pop(key, None)
            self._discard(rec)
            self._update_gauges()
            return None
        if weights_version is not None and str(
                meta.get("weights_version")) != str(weights_version):
            # header says stale even though the record field matched —
            # treat exactly like the record-level check
            self.stale_refused.inc()
            self._records.pop(key, None)
            self._discard(rec)
            self._update_gauges()
            return None
        self._records.move_to_end(key)
        return rec, meta, arrays

    def pop(self, key, restored=False):
        """Remove one record (after a successful restore, or to drop
        it). Counts a restore when ``restored``."""
        rec = self._records.pop(key, None)
        if rec is None:
            return
        if restored:
            self.restores.inc(label=rec.tier)
            self._rec.note("kv_restore", tier=rec.tier,
                           bytes=rec.nbytes, tokens=len(rec.tokens))
            self._discard(rec, count=False)
        else:
            self._discard(rec)
        self._update_gauges()

    def _discard(self, rec, count=True):
        self._bytes[rec.tier] -= rec.nbytes
        kids = self._children.get(rec.parent)
        if kids is not None:
            kids.discard(rec.key)
            if not kids:
                self._children.pop(rec.parent, None)
        if rec.path is not None:
            try:
                os.unlink(rec.path)
            except OSError:
                pass
        if count:
            self.dropped.inc(label="evicted")

    def flush(self, reason="flush"):
        """Drop every record — the weight-swap seam (spilled pages
        computed under rotated-out weights can never restore; keeping
        them would only waste the budget) and engine close."""
        n = len(self._records)
        for rec in list(self._records.values()):
            self._discard(rec, count=False)
        if n:
            self.dropped.inc(n, label=reason)
        self._records.clear()
        self._children.clear()
        self._update_gauges()
        return n

    # -------------------------------------------------------- accounting
    def _update_gauges(self):
        for tier in (TIER_HOST, TIER_DISK):
            self.tier_pages.set(
                float(sum(1 for r in self._records.values()
                          if r.tier == tier)), tier=tier)
            self.tier_bytes.set(float(self._bytes[tier]), tier=tier)

    def stats(self):
        host = sum(1 for r in self._records.values()
                   if r.tier == TIER_HOST)
        return {
            "pages": {TIER_HOST: host,
                      TIER_DISK: len(self._records) - host},
            "bytes": dict(self._bytes),
            "host_budget_bytes": self.host_budget_bytes,
            "spills": self.spills.by_label(),
            "restores": self.restores.by_label(),
            "crc_refused": int(self.crc_refused.value),
            "stale_refused": int(self.stale_refused.value),
            "dropped": int(self.dropped.value),
        }
