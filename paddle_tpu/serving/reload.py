"""Live weight reload — checkpoint rotation on a RUNNING engine.

Pushing a new checkpoint used to mean restarting every engine (and
re-paying every compile). This module swaps weights in place instead,
with the same integrity discipline the checkpoint runtime already
enforces on restore:

1. **Verify** — the candidate directory must pass the PR 5
   manifest/CRC protocol (``checkpoint.commit.verify_checkpoint``); a
   torn or bit-rotted publish is refused and the engine keeps serving
   the last committed weights.
2. **Load** — the checkpoint's ``model`` state loads into a template
   net (a deepcopy of the serving net by default, or a caller-supplied
   float-architecture template). When the engine serves QUANTIZED
   weights, ``quantization.serving.quantize_for_serving`` runs inside
   the swap — a bf16 training checkpoint publishes as int8 serving
   weights without the training side knowing serving's format.
3. **Validate** — the harvested param/buffer trees must match the
   engine's current snapshot key-for-key in shape and dtype. The
   compiled programs are shape-specialized; an incompatible checkpoint
   is refused outright rather than recompiled into silently.
4. **Apply at a step boundary** — the staged swap is committed only
   when NO request is in flight: admission pauses, in-flight requests
   finish on the old weights, then params/buffers/``weights_version``
   swap in one host-side assignment block and admission resumes. A
   request therefore always runs start-to-finish under ONE weights
   version (stamped on its handle at admission), and the attached
   prefill transport's ``expected_weights_version`` moves with the
   swap so the worker's version-skew refusal keeps disaggregation
   exact during the rotation window. The same boundary fires the
   engine's ``_on_weights_swapped`` hook: the paged engine FLUSHES its
   prefix cache there (every cached page holds KV computed under the
   outgoing weights — and the store's keys re-root on the new
   ``weights_version`` as a second line of defense), so a post-swap
   request can never adopt stale-weights pages.

Steps 1–3 (``prepare``) are pure and run OFF the engine's step loop —
an HTTP handler thread does the disk reads and quantization while the
driver keeps decoding; only step 4 needs the engine's single-threaded
discipline. Every outcome lands in
``paddle_serving_reloads_total{outcome}``; the admission-pause window
(the worst-case TTFT a queued request gained) lands in
``paddle_serving_reload_ttft_spike_seconds``.
"""
from __future__ import annotations

import logging
import os

from . import chaos as _chaos

logger = logging.getLogger("paddle_tpu.serving.reload")


class ReloadError(RuntimeError):
    """Programming-error side of reload (bad arguments); operational
    failures (torn checkpoint, incompatible state) come back as a
    failed :class:`StagedReload`, never an exception — a bad publish
    must degrade to "keep serving", not to a crashed replica."""


class StagedReload:
    """A prepared (verified, loaded, validated) weight swap, plus its
    outcome trail once committed/applied."""

    __slots__ = ("ok", "outcome", "error", "params", "buffers",
                 "weights_version", "step", "path", "staged_at")

    def __init__(self, ok, outcome, *, error=None, params=None,
                 buffers=None, weights_version=None, step=None,
                 path=None):
        self.ok = bool(ok)
        self.outcome = outcome
        self.error = error
        self.params = params
        self.buffers = buffers
        self.weights_version = weights_version
        self.step = step
        self.path = path
        self.staged_at = None

    @property
    def applied(self):
        return self.outcome == "applied"

    def to_json(self):
        return {
            "ok": self.ok,
            "outcome": self.outcome,
            "error": self.error,
            "weights_version": self.weights_version,
            "step": self.step,
            "path": self.path,
        }

    def __repr__(self):
        return (f"StagedReload(ok={self.ok}, outcome={self.outcome!r}, "
                f"version={self.weights_version!r}, step={self.step})")


def resolve_checkpoint_dir(path):
    """``path`` may be a committed step directory (has a manifest) or a
    checkpoint ROOT — then the newest committed step is chosen, exactly
    like restore. Returns None when nothing committed exists."""
    from ..checkpoint import commit as commit_mod

    path = str(path)
    if commit_mod.read_manifest(path) is not None:
        return path
    if os.path.isdir(path):
        return commit_mod.latest_committed(path)
    return None


def _is_quantized(net):
    from ..quantization.serving import QuantizedLinear

    return any(
        isinstance(m, QuantizedLinear) for _, m in net.named_sublayers()
    )


def _harvest(net):
    return (
        {k: p.value for k, p in net.named_parameters()},
        {k: b.value for k, b in net.named_buffers()},
    )


def _validate(cur_params, cur_buffers, new_params, new_buffers):
    """Key/shape/dtype compatibility of the new snapshot against the
    one the compiled programs were built for. Returns a problem string
    or None."""
    import jax.numpy as jnp

    for kind, cur, new in (("param", cur_params, new_params),
                           ("buffer", cur_buffers, new_buffers)):
        missing = sorted(set(cur) - set(new))
        extra = sorted(set(new) - set(cur))
        if missing or extra:
            return (f"{kind} keys differ: missing {missing[:3]}, "
                    f"unexpected {extra[:3]}")
        for k, v in cur.items():
            nv = new[k]
            if tuple(getattr(nv, "shape", ())) != tuple(
                getattr(v, "shape", ())
            ):
                return (f"{kind} {k}: shape {tuple(nv.shape)} != "
                        f"serving {tuple(v.shape)}")
            if jnp.dtype(nv.dtype) != jnp.dtype(v.dtype):
                return (f"{kind} {k}: dtype {nv.dtype} != serving "
                        f"{v.dtype}")
    return None


def prepare_state_swap(net, cur_params, cur_buffers, ckpt_dir, *,
                       weights_version=None, template_net=None,
                       verify_level="full"):
    """The shared prepare path (serving engines AND the fleet prefill
    worker): verify → load → (quantize) → harvest → validate. Pure —
    touches neither ``net`` nor the current snapshot; returns a
    :class:`StagedReload` either way."""
    from ..checkpoint import commit as commit_mod
    from ..distributed.checkpoint.save_load import load_state_dict

    try:
        _chaos.poke("reload.prepare", path=str(ckpt_dir))
    except BaseException as e:
        return StagedReload(False, "error", error=repr(e),
                            path=str(ckpt_dir))
    path = resolve_checkpoint_dir(ckpt_dir)
    if path is None:
        return StagedReload(
            False, "no_checkpoint",
            error=f"no committed checkpoint under {ckpt_dir!r}",
            path=str(ckpt_dir),
        )
    problems = commit_mod.verify_checkpoint(path, level=verify_level)
    if problems:
        logger.warning("reload: refusing %s: %s", path, problems[:4])
        return StagedReload(
            False, "verify_failed",
            error="; ".join(problems[:4]), path=path,
        )
    manifest = commit_mod.read_manifest(path)
    step = int(manifest["step"])
    quantized = _is_quantized(net)
    try:
        # a template may be a net INSTANCE or a zero-arg factory; a
        # Layer is itself callable (its forward), so only non-Layer
        # callables are factories. Resolution sits inside the try: a
        # throwing factory is a load_error outcome, never an escape
        # from the never-raises contract.
        from ..nn.layer.layers import Layer

        if template_net is not None and callable(template_net) \
                and not isinstance(template_net, Layer):
            template = template_net()
        else:
            template = template_net
        if template is None:
            # serving-format template built from the SNAPSHOT arrays,
            # not the live net: state_dict keys are exactly
            # named_parameters + named_buffers, and fresh Tensor
            # wrappers around the current snapshot give load_state_dict
            # the right shapes/dtypes/shardings with zero copies.
            # Crucially this never touches the net object — the engine
            # may be TRACING on its own thread right now (tracers
            # swapped into the Layer attrs), and a deepcopy would race
            # it. Works whenever the checkpoint was saved from the
            # same (possibly quantized) structure.
            from ..core.tensor import Tensor

            tmpl = {
                k: Tensor(v, stop_gradient=True)
                for k, v in {**cur_params, **cur_buffers}.items()
            }
            load_state_dict({"model": tmpl}, path)
            new_params = {k: tmpl[k].value for k in cur_params}
            new_buffers = {k: tmpl[k].value for k in cur_buffers}
        else:
            state = {"model": template.state_dict()}
            load_state_dict(state, path)
            src = template
            if quantized and not _is_quantized(template):
                # the int8 publish path: a float training checkpoint
                # becomes serving-format weights inside the swap
                from ..quantization.serving import quantize_for_serving

                src = quantize_for_serving(template)
            new_params, new_buffers = _harvest(src)
    except KeyError as e:
        hint = (" (engine serves quantized weights — pass a "
                "float-architecture template_net so the checkpoint "
                "can be quantized inside the swap)"
                if quantized and template_net is None else "")
        return StagedReload(
            False, "incompatible",
            error=f"checkpoint does not match serving net: {e}{hint}",
            path=path, step=step,
        )
    except Exception as e:
        return StagedReload(
            False, "load_error", error=repr(e), path=path, step=step,
        )
    problem = _validate(cur_params, cur_buffers, new_params, new_buffers)
    if problem is not None:
        return StagedReload(
            False, "incompatible", error=problem, path=path, step=step,
        )
    version = (str(weights_version) if weights_version is not None
               else f"ckpt-{step}")
    return StagedReload(
        True, "staged", params=new_params, buffers=new_buffers,
        weights_version=version, step=step, path=path,
    )
