"""Long-tail tensor ops (reference parity: python/paddle/tensor/* rows
not covered by the core modules — unverified, mount empty).

Every op is one pure jnp function through core.dispatch (eager per-op
jit + autograd via jax.vjp; fused inside whole-step jit). Ops with
integer/bool outputs are declared nondiff.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ._helpers import binary, normalize_axis, static_int_list, unary

# ----------------------------------------------------------- elementwise
rad2deg = unary("rad2deg", jnp.rad2deg)
deg2rad = unary("deg2rad", jnp.deg2rad)
sinc = unary("sinc", jnp.sinc)
i1 = unary("i1", lambda x: jax.scipy.special.i1(x))
sgn = unary("sgn", jnp.sign)
signbit = unary("signbit", jnp.signbit, nondiff=True)
isneginf = unary("isneginf", jnp.isneginf, nondiff=True)
isposinf = unary("isposinf", jnp.isposinf, nondiff=True)
nextafter = binary("nextafter", jnp.nextafter)
ldexp = binary("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
heaviside = binary("heaviside", jnp.heaviside)
gcd = binary("gcd", jnp.gcd, nondiff=True)
lcm = binary("lcm", jnp.lcm, nondiff=True)


def _polygamma(x, *, n):
    return jax.scipy.special.polygamma(n, x)


def polygamma(x, n, name=None):
    return dispatch.apply("polygamma", _polygamma, (x,), {"n": int(n)})


def _frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


def frexp(x, name=None):
    m, e = dispatch.apply("frexp", _frexp, (x,), nondiff=True)
    return m, e


# -------------------------------------------------------------- stacking
def _along(fn):
    def impl(*xs):
        return fn(xs)

    return impl


def _stack_op(name, fn):
    impl = _along(fn)  # stable identity -> per-op jit cache hits

    def op(x, name=None):
        return dispatch.apply(op_name, impl, tuple(x))

    op_name = name
    op.__name__ = op.__qualname__ = name
    return op


hstack = _stack_op("hstack", jnp.hstack)
vstack = _stack_op("vstack", jnp.vstack)
dstack = _stack_op("dstack", jnp.dstack)
column_stack = _stack_op("column_stack", jnp.column_stack)
row_stack = _stack_op("row_stack", jnp.vstack)


def atleast_1d(*xs, name=None):
    outs = [
        dispatch.apply("atleast_1d", jnp.atleast_1d, (x,)) for x in xs
    ]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = [
        dispatch.apply("atleast_2d", jnp.atleast_2d, (x,)) for x in xs
    ]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = [
        dispatch.apply("atleast_3d", jnp.atleast_3d, (x,)) for x in xs
    ]
    return outs[0] if len(outs) == 1 else outs


_block_diag_impl = _along(lambda xs: jax.scipy.linalg.block_diag(*xs))


def block_diag(inputs, name=None):
    return dispatch.apply("block_diag", _block_diag_impl, tuple(inputs))


# ---------------------------------------------------------- manipulation
def _rot90(x, *, k, axes):
    return jnp.rot90(x, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return dispatch.apply(
        "rot90", _rot90, (x,), {"k": int(k), "axes": tuple(axes)}
    )


def fliplr(x, name=None):
    return dispatch.apply("fliplr", jnp.fliplr, (x,))


def flipud(x, name=None):
    return dispatch.apply("flipud", jnp.flipud, (x,))


def _unflatten(x, *, axis, shape):
    s = list(x.shape)
    return jnp.reshape(x, tuple(s[:axis]) + tuple(shape) + tuple(s[axis + 1:]))


def unflatten(x, axis, shape, name=None):
    ax = int(axis) % max(len(x.shape), 1)
    return dispatch.apply(
        "unflatten", _unflatten, (x,),
        {"axis": ax, "shape": static_int_list(shape)},
    )


def _unfold(x, *, axis, size, step):
    # sliding windows along axis (torch/paddle Tensor.unfold semantics):
    # result appends a window dim of length `size`
    n = (x.shape[axis] - size) // step + 1
    idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
    moved = jnp.moveaxis(x, axis, 0)
    win = moved[idx]  # [n, size, ...rest]
    win = jnp.moveaxis(win, (0, 1), (axis, len(x.shape)))
    return win


def unfold(x, axis, size, step, name=None):
    ax = int(axis) % len(x.shape)
    return dispatch.apply(
        "unfold", _unfold, (x,),
        {"axis": ax, "size": int(size), "step": int(step)},
    )


def _diagflat(x, *, offset):
    return jnp.diagflat(x, k=offset)


def diagflat(x, offset=0, name=None):
    return dispatch.apply("diagflat", _diagflat, (x,), {"offset": int(offset)})


def vander(x, n=None, increasing=False, name=None):
    cols = int(n) if n is not None else int(x.shape[0])

    def _vander(v, *, cols, increasing):
        return jnp.vander(v, N=cols, increasing=increasing)

    return dispatch.apply(
        "vander", _vander, (x,),
        {"cols": cols, "increasing": bool(increasing)},
    )


def _slice_scatter(x, value, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    return x.at[tuple(idx)].set(value)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    return dispatch.apply(
        "slice_scatter", _slice_scatter, (x, value),
        {
            "axes": static_int_list(axes),
            "starts": static_int_list(starts),
            "ends": static_int_list(ends),
            "strides": static_int_list(strides),
        },
    )


def index_add(x, index, value, axis=0, name=None):
    def _impl(xv, iv, vv, *, axis):
        moved = jnp.moveaxis(xv, axis, 0)
        vmoved = jnp.moveaxis(vv, axis, 0)
        out = moved.at[iv.astype(jnp.int32)].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return dispatch.apply(
        "index_add", _impl, (x, index, value),
        {"axis": int(axis) % len(x.shape)},
    )


def _index_fill(x, index, *, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    out = moved.at[index.astype(jnp.int32)].set(value)
    return jnp.moveaxis(out, 0, axis)


def index_fill(x, index, axis, value, name=None):
    return dispatch.apply(
        "index_fill", _index_fill, (x, index),
        {"axis": int(axis) % len(x.shape), "value": float(value)},
    )


def masked_scatter(x, mask, value, name=None):
    """Fill True positions of ``mask`` with consecutive values (paddle
    semantics: value is consumed in row-major order)."""

    def _impl(xv, mv, vv):
        flat_x = xv.reshape(-1)
        flat_m = mv.reshape(-1)
        flat_v = vv.reshape(-1)
        # position k in x takes value[#True before k]
        rank = jnp.cumsum(flat_m) - 1
        take = jnp.clip(rank, 0, flat_v.shape[0] - 1)
        return jnp.where(
            flat_m, flat_v[take], flat_x
        ).reshape(xv.shape)

    return dispatch.apply("masked_scatter", _impl, (x, mask, value))


def take(x, index, mode="raise", name=None):
    def _take(xv, iv, *, mode):
        m = {"raise": "clip"}.get(mode, mode)  # no host-side raise in XLA
        return jnp.take(xv.reshape(-1), iv.astype(jnp.int32), mode=m)

    return dispatch.apply("take", _take, (x, index), {"mode": mode})


# ------------------------------------------------------------ reductions
def _cumextreme_impl(x, *, axis, combine):
    fn = jnp.maximum if combine == "max" else jnp.minimum
    vals = jax.lax.associative_scan(fn, x, axis=axis)
    n = x.shape[axis]
    ar = jnp.expand_dims(
        jnp.arange(n, dtype=jnp.int32),
        [d for d in range(x.ndim) if d != axis],
    )
    hit = (x == vals)
    # last index achieving the running extreme (paddle ties-to-last)
    idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(hit, ar, -1), axis=axis
    )
    return vals, idx


def _cumextreme(name, x, axis, combine):
    xv = x
    if axis is None:
        xv = xv.reshape([-1])
        axis = 0
    return dispatch.apply(
        name, _cumextreme_impl, (xv,),
        {"axis": int(axis) % max(len(xv.shape), 1), "combine": combine},
    )


def cummax(x, axis=None, name=None):
    return _cumextreme("cummax", x, axis, "max")


def cummin(x, axis=None, name=None):
    return _cumextreme("cummin", x, axis, "min")


def _trapezoid(y, x, *, dx, axis):
    if x is None:
        return jnp.trapezoid(y, dx=dx, axis=axis)
    return jnp.trapezoid(y, x, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is None:
        return dispatch.apply(
            "trapezoid", lambda yv, *, dx, axis: jnp.trapezoid(
                yv, dx=dx, axis=axis
            ),
            (y,), {"dx": 1.0 if dx is None else float(dx), "axis": int(axis)},
        )
    return dispatch.apply(
        "trapezoid_x", lambda yv, xv, *, axis: jnp.trapezoid(
            yv, xv, axis=axis
        ),
        (y, x), {"axis": int(axis)},
    )


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return dispatch.apply(
        "nanquantile",
        lambda xv, *, q, axis, keepdim: jnp.nanquantile(
            xv, jnp.asarray(q), axis=axis, keepdims=keepdim
        ),
        (x,),
        {"q": float(q) if not isinstance(q, (list, tuple)) else tuple(q),
         "axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )


# ----------------------------------------------------------- statistics
def _histogram(x, *, bins, lo, hi):
    h, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return h


def histogram(input, bins=100, min=0, max=0, name=None):
    import numpy as _np

    lo, hi = float(min), float(max)
    if lo == 0 and hi == 0:
        # data-dependent range must be static for the compiled op:
        # resolve it host-side (eager semantics, as in the reference)
        v = _np.asarray(
            input.value if isinstance(input, Tensor) else input
        )
        lo, hi = float(v.min()), float(v.max())
    return dispatch.apply(
        "histogram", _histogram, (input,),
        {"bins": int(bins), "lo": lo, "hi": hi}, nondiff=True,
    )


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    rng = None
    if ranges is not None:
        flat = [float(v) for v in _host_list(ranges)]
        rng = tuple(
            (flat[i], flat[i + 1]) for i in range(0, len(flat), 2)
        )

    def _impl(*vals, bins, density, rng):
        xv = vals[0]
        wv = vals[1] if len(vals) > 1 else None
        h, edges = jnp.histogramdd(
            xv, bins=bins, range=rng, density=density, weights=wv
        )
        return (h,) + tuple(edges)

    args = (x,) if weights is None else (x, weights)
    out = dispatch.apply(
        "histogramdd", _impl, args,
        {"bins": bins if isinstance(bins, int) else tuple(bins),
         "density": bool(density), "rng": rng}, nondiff=True,
    )
    return out[0], list(out[1:])


def bincount(x, weights=None, minlength=0, name=None):
    import numpy as _np

    # output length is data-dependent: resolve host-side for the static
    # shape the compiled op needs (eager semantics, as in the reference)
    v = _np.asarray(x.value if isinstance(x, Tensor) else x)
    length = max(int(minlength), int(v.max()) + 1 if v.size else 0, 1)
    if weights is None:
        return dispatch.apply(
            "bincount",
            lambda xv, *, length: jnp.bincount(
                xv.astype(jnp.int32), length=length
            ),
            (x,), {"length": length}, nondiff=True,
        )
    return dispatch.apply(
        "bincount_w",
        lambda xv, wv, *, length: jnp.bincount(
            xv.astype(jnp.int32), weights=wv, length=length
        ),
        (x, weights), {"length": length},
    )


def _cov(x, *, rowvar, ddof):
    return jnp.cov(x, rowvar=rowvar, ddof=ddof)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    if fweights is not None or aweights is not None:
        return dispatch.apply(
            "cov_w",
            lambda xv, *, rowvar, ddof, fw, aw: jnp.cov(
                xv, rowvar=rowvar, ddof=ddof,
                fweights=None if fw is None else jnp.asarray(fw),
                aweights=None if aw is None else jnp.asarray(aw),
            ),
            (x,),
            {"rowvar": bool(rowvar), "ddof": 1 if ddof else 0,
             "fw": None if fweights is None else tuple(
                 int(v) for v in _host_list(fweights)),
             "aw": None if aweights is None else tuple(
                 float(v) for v in _host_list(aweights))},
            cache=False,
        )
    return dispatch.apply(
        "cov", _cov, (x,), {"rowvar": bool(rowvar), "ddof": 1 if ddof else 0}
    )


def _host_list(v):
    import numpy as _np

    return _np.asarray(
        v.numpy() if hasattr(v, "numpy") else v
    ).reshape(-1).tolist()


def corrcoef(x, rowvar=True, name=None):
    return dispatch.apply(
        "corrcoef",
        lambda xv, *, rowvar: jnp.corrcoef(xv, rowvar=rowvar),
        (x,), {"rowvar": bool(rowvar)},
    )


# ------------------------------------------------------------- distance
def dist(x, y, p=2, name=None):
    def _dist(xv, yv, *, p):
        import math as _math

        d = (xv - yv).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(xv.dtype)
        if _math.isinf(p):
            return jnp.max(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return dispatch.apply("dist", _dist, (x, y), {"p": float(p)})


def cdist(x, y, p=2.0, compute_mode=None, name=None):
    def _cdist(xv, yv, *, p):
        diff = xv[..., :, None, :] - yv[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)

    return dispatch.apply("cdist", _cdist, (x, y), {"p": float(p)})


def pdist(x, p=2.0, name=None):
    def _pdist(xv, *, p):
        n = xv.shape[0]
        diff = xv[:, None, :] - xv[None, :, :]
        if p == 2.0:
            d = jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
        else:
            d = jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return d[iu]

    return dispatch.apply("pdist", _pdist, (x,), {"p": float(p)})


# ------------------------------------------------------------ misc/logic
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return dispatch.apply(
        "isin",
        lambda xv, tv, *, invert: jnp.isin(xv, tv, invert=invert),
        (x, test_x), {"invert": bool(invert)}, nondiff=True,
    )


def mv(x, vec, name=None):
    return dispatch.apply("mv", lambda a, b: a @ b, (x, vec))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(
            tuple(static_int_list(a)) if isinstance(a, (list, tuple))
            else int(a)
            for a in ax
        )
    return dispatch.apply(
        "tensordot",
        lambda a, b, *, axes: jnp.tensordot(a, b, axes=axes),
        (x, y), {"axes": ax},
    )


def renorm(x, p, axis, max_norm, name=None):
    def _renorm(xv, *, p, axis, max_norm):
        moved = jnp.moveaxis(xv, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(
            norms > max_norm, max_norm / (norms + 1e-12), 1.0
        )
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return dispatch.apply(
        "renorm", _renorm, (x,),
        {"p": float(p), "axis": int(axis) % len(x.shape),
         "max_norm": float(max_norm)},
    )


def broadcast_shape(x_shape, y_shape):
    import numpy as _np

    return list(
        _np.broadcast_shapes(tuple(x_shape), tuple(y_shape))
    )


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    import numpy as _np

    n = int(x.shape[0])
    pool = (
        itertools.combinations_with_replacement(range(n), r)
        if with_replacement else itertools.combinations(range(n), r)
    )
    idx = _np.asarray(list(pool), dtype=_np.int32)
    if idx.size == 0:
        idx = idx.reshape(0, r)

    def _comb(xv, *, idx_tuple, r):
        iarr = jnp.asarray(idx_tuple, jnp.int32).reshape(-1, r)
        return xv[iarr]

    return dispatch.apply(
        "combinations", _comb, (x,),
        {"idx_tuple": tuple(map(tuple, idx.tolist())), "r": int(r)},
    )


def polar(abs, angle, name=None):
    return dispatch.apply(
        "polar",
        lambda a, t: (a * jnp.cos(t) + 1j * a * jnp.sin(t)).astype(
            jnp.complex64
        ),
        (abs, angle),
    )


def view_as_complex(x, name=None):
    return dispatch.apply(
        "view_as_complex",
        lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
        (x,),
    )


def view_as_real(x, name=None):
    return dispatch.apply(
        "view_as_real",
        lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
        (x,),
    )


def poisson(x, name=None):
    from ..core import random as random_mod

    def _poisson(lam, *, key):
        return jax.random.poisson(key, lam).astype(lam.dtype)

    return dispatch.apply(
        "poisson", _poisson, (x,), {"key": random_mod.next_key()},
        cache=False, nondiff=True,
    )
