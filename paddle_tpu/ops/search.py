"""Search/sort ops: argmax/argmin/argsort/sort/topk/nonzero/searchsorted/kthvalue/mode.

Reference parity: python/paddle/tensor/search.py (unverified, mount empty).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core import dispatch, tape
from ..core.dtypes import convert_dtype
from ..core.tensor import Tensor
from ._helpers import normalize_axis


def _argmax(x, *, axis, keepdim):
    if axis is None:
        return jnp.argmax(x.reshape(-1)).astype(jnp.int64)
    out = jnp.argmax(x, axis=axis).astype(jnp.int64)
    return jnp.expand_dims(out, axis) if keepdim else out


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = dispatch.apply(
        "argmax",
        _argmax,
        (x,),
        {"axis": normalize_axis(axis), "keepdim": bool(keepdim)},
        nondiff=True,
    )
    return out.astype(convert_dtype(dtype)) if dtype != "int64" else out


def _argmin(x, *, axis, keepdim):
    if axis is None:
        return jnp.argmin(x.reshape(-1)).astype(jnp.int64)
    out = jnp.argmin(x, axis=axis).astype(jnp.int64)
    return jnp.expand_dims(out, axis) if keepdim else out


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = dispatch.apply(
        "argmin",
        _argmin,
        (x,),
        {"axis": normalize_axis(axis), "keepdim": bool(keepdim)},
        nondiff=True,
    )
    return out.astype(convert_dtype(dtype)) if dtype != "int64" else out


def _argsort(x, *, axis, descending, stable):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out.astype(jnp.int64)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return dispatch.apply(
        "argsort",
        _argsort,
        (x,),
        {"axis": int(axis), "descending": bool(descending), "stable": bool(stable)},
        nondiff=True,
    )


def _sort(x, *, axis, descending, stable):
    return jnp.sort(x, axis=axis, stable=stable, descending=descending)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return dispatch.apply(
        "sort",
        _sort,
        (x,),
        {"axis": int(axis), "descending": bool(descending), "stable": bool(stable)},
    )


def _topk(x, *, k, axis, largest, sorted):
    ax = axis if axis is not None else -1
    if largest:
        idx = jnp.argsort(x, axis=ax, descending=True)
    else:
        idx = jnp.argsort(x, axis=ax)
    idx = jnp.take(idx, jnp.arange(k), axis=ax)
    vals = jnp.take_along_axis(x, idx, axis=ax)
    return vals, idx.astype(jnp.int64)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    out = dispatch.apply(
        "topk",
        _topk,
        (x,),
        {
            "k": int(k),
            "axis": normalize_axis(axis),
            "largest": bool(largest),
            "sorted": bool(sorted),
        },
    )
    return out[0], out[1]


def _kthvalue(x, *, k, axis, keepdim):
    ax = axis
    vals = jnp.sort(x, axis=ax)
    idxs = jnp.argsort(x, axis=ax).astype(jnp.int64)
    v = jnp.take(vals, k - 1, axis=ax)
    i = jnp.take(idxs, k - 1, axis=ax)
    if keepdim:
        v = jnp.expand_dims(v, ax)
        i = jnp.expand_dims(i, ax)
    return v, i


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    out = dispatch.apply(
        "kthvalue",
        _kthvalue,
        (x,),
        {"k": int(k), "axis": int(axis), "keepdim": bool(keepdim)},
    )
    return out[0], out[1]


def _mode(x, *, axis, keepdim):
    sorted_x = jnp.sort(x, axis=axis)
    # mode = most frequent; for float data fall back to median-of-sorted trick
    n = x.shape[axis]
    runs = jnp.concatenate(
        [
            jnp.ones(sorted_x.shape[:axis] + (1,) + sorted_x.shape[axis + 1 :], bool),
            jnp.take(sorted_x, jnp.arange(1, n), axis=axis)
            != jnp.take(sorted_x, jnp.arange(0, n - 1), axis=axis),
        ],
        axis=axis,
    )
    run_id = jnp.cumsum(runs, axis=axis)
    # count run lengths via segment trick: for each pos, count matches of its id
    counts = jnp.sum(
        run_id[..., None] == jnp.moveaxis(run_id, axis, -1)[..., None, :], axis=-1
    ) if axis == x.ndim - 1 else None
    if counts is None:
        raise NotImplementedError("mode only supports the last axis")
    best = jnp.argmax(counts, axis=axis)
    v = jnp.take_along_axis(sorted_x, best[..., None], axis=axis)[..., 0]
    i = jnp.argmax(x == v[..., None], axis=axis).astype(jnp.int64)
    if keepdim:
        v, i = v[..., None], i[..., None]
    return v, i


def mode(x, axis=-1, keepdim=False, name=None):
    ax = int(axis) % x.ndim
    if ax != x.ndim - 1:
        raise NotImplementedError("mode currently supports the last axis only")
    out = dispatch.apply(
        "mode", _mode, (x,), {"axis": ax, "keepdim": bool(keepdim)}
    )
    return out[0], out[1]


def _searchsorted(a, v, *, right):
    return jnp.searchsorted(a, v, side="right" if right else "left").astype(jnp.int64)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = dispatch.apply(
        "searchsorted",
        _searchsorted,
        (sorted_sequence, values),
        {"right": bool(right)},
        nondiff=True,
    )
    return out.astype(jnp.int32) if out_int32 else out


def nonzero(x, as_tuple=False, name=None):
    if tape.in_trace():
        raise RuntimeError(
            "nonzero has a data-dependent output shape and cannot run inside "
            "a jit trace on TPU"
        )
    xv = np.asarray(x.value if isinstance(x, Tensor) else x)
    idx = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def index_put(x, indices, value, accumulate=False, name=None):
    indices_u = tuple(
        i.value if isinstance(i, Tensor) else i for i in indices
    )

    def _ip(xv, vv):
        return xv.at[indices_u].add(vv) if accumulate else xv.at[indices_u].set(vv)

    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value, x.value.dtype))
    return dispatch.apply("index_put", _ip, (x, value), cache=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
