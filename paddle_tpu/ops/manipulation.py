"""Shape/layout/indexing ops.

Reference parity: python/paddle/tensor/manipulation.py + phi kernels
(unverified, mount empty). All static-shape ops trace cleanly under jit;
dynamic-output ops (nonzero/unique/masked_select) are eager-only by nature —
they raise a clear error inside traces, matching the TPU/XLA static-shape
execution model.
"""
from __future__ import annotations

import builtins

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import enforce as _enf
from ..core.dtypes import convert_dtype
from ..core.tensor import Tensor
from ._helpers import normalize_axis, static_int_list

# ----------------------------------------------------------------- basic


def _cast(x, *, dtype):
    return x.astype(dtype)


def cast(x, dtype, name=None):
    d = convert_dtype(dtype)
    return dispatch.apply("cast", _cast, (x,), {"dtype": np.dtype(d).name})


def _assign(x):
    return jnp.asarray(x)


def assign(x, output=None, name=None):
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    out = dispatch.apply("assign", _assign, (x,))
    if output is not None:
        return output._inplace(lambda _alias: out)
    return out


def _reshape(x, *, shape):
    shape = list(shape)
    # paddle: 0 means "copy this dim from input"
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    tgt = static_int_list(shape)
    if isinstance(tgt, int):  # scalar target shape
        tgt = [tgt]
    if hasattr(x, "shape"):
        n = int(np.prod([int(d) for d in x.shape])) if len(x.shape) else 1
        known = int(np.prod([d for d in tgt if d not in (-1, 0)]) or 1)
        zeros = [i for i, d in enumerate(tgt) if d == 0]
        if not zeros:  # 0-dims copy input dims; skip the cheap check then
            if -1 in tgt:
                _enf.enforce(
                    known != 0 and n % known == 0, "reshape",
                    "cannot infer -1: input shape {} ({} elements) is "
                    "not divisible by the known target dims {}",
                    tuple(x.shape), n, tgt,
                )
            else:
                _enf.enforce(
                    known == n, "reshape",
                    "target shape {} has {} elements but input shape {} "
                    "has {}", tgt, known, tuple(x.shape), n,
                )
    return dispatch.apply(
        "reshape", _reshape, (x,), {"shape": tgt}
    )


def reshape_(x, shape, name=None):
    return x._inplace(reshape, shape)


def _transpose(x, *, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return dispatch.apply(
        "transpose", _transpose, (x,), {"perm": static_int_list(perm)}
    )


def _t(x):
    if x.ndim < 2:
        return x
    return jnp.swapaxes(x, -1, -2)


def t(x, name=None):
    return dispatch.apply("t", _t, (x,))


matrix_transpose = t


def _swapaxes(x, *, a, b):
    return jnp.swapaxes(x, a, b)


def swapaxes(x, axis0, axis1, name=None):
    return dispatch.apply(
        "swapaxes", _swapaxes, (x,), {"a": int(axis0), "b": int(axis1)}
    )


transpose_ = swapaxes  # not paddle API; kept private-ish


def _moveaxis(x, *, src, dst):
    return jnp.moveaxis(x, src, dst)


def moveaxis(x, source, destination, name=None):
    return dispatch.apply(
        "moveaxis",
        _moveaxis,
        (x,),
        {"src": static_int_list(source), "dst": static_int_list(destination)},
    )


def _flatten(x, *, start, stop):
    shape = x.shape
    nd = len(shape)
    start_ = start % nd if nd else 0
    stop_ = stop % nd if nd else 0
    new_shape = (
        list(shape[:start_])
        + [int(np.prod(shape[start_ : stop_ + 1])) if nd else 1]
        + list(shape[stop_ + 1 :])
    )
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return dispatch.apply(
        "flatten", _flatten, (x,), {"start": int(start_axis), "stop": int(stop_axis)}
    )


def _squeeze(x, *, axis):
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def squeeze(x, axis=None, name=None):
    return dispatch.apply("squeeze", _squeeze, (x,), {"axis": normalize_axis(axis)})


def squeeze_(x, axis=None, name=None):
    return x._inplace(squeeze, axis)


def _unsqueeze(x, *, axis):
    axes = axis if isinstance(axis, tuple) else (axis,)
    return jnp.expand_dims(x, axes)


def unsqueeze(x, axis, name=None):
    return dispatch.apply(
        "unsqueeze", _unsqueeze, (x,), {"axis": normalize_axis(axis)}
    )


def unsqueeze_(x, axis, name=None):
    return x._inplace(unsqueeze, axis)


# ------------------------------------------------------------ joining/splitting


def _concat(*xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    xs = list(x)
    _enf.enforce(len(xs) > 0, "concat", "input list must be non-empty")
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    nd0 = len(xs[0].shape) if hasattr(xs[0], "shape") else None
    for i, t in enumerate(xs[1:], 1):
        if nd0 is not None and hasattr(t, "shape"):
            _enf.enforce(
                len(t.shape) == nd0, "concat",
                "all inputs must have the same ndim; input 0 has shape "
                "{} but input {} has shape {}",
                tuple(xs[0].shape), i, tuple(t.shape),
            )
    return dispatch.apply("concat", _concat, tuple(xs), {"axis": int(axis)})


def _stack(*xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return dispatch.apply("stack", _stack, tuple(x), {"axis": int(axis)})


def _split(x, *, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    # sections is sizes list, possibly with one -1
    sizes = list(sections)
    if -1 in sizes:
        known = builtins.sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = x.shape[axis] - known
    idx = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    sec = (
        int(num_or_sections)
        if isinstance(num_or_sections, int)
        else tuple(int(s) for s in num_or_sections)
    )
    out = dispatch.apply(
        "split", _split, (x,), {"sections": sec, "axis": int(axis)}
    )
    return list(out)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def _unbind(x, *, axis):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


def unbind(x, axis=0, name=None):
    return list(dispatch.apply("unbind", _unbind, (x,), {"axis": int(axis)}))


unstack = unbind

# ------------------------------------------------------------------ expansion


def _tile(x, *, reps):
    return jnp.tile(x, reps)


def tile(x, repeat_times, name=None):
    return dispatch.apply(
        "tile", _tile, (x,), {"reps": static_int_list(repeat_times)}
    )


def _expand(x, *, shape):
    shape = list(shape)
    # paddle: -1 means keep input dim
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1 and i >= offset:
            shape[i] = x.shape[i - offset]
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    return dispatch.apply("expand", _expand, (x,), {"shape": static_int_list(shape)})


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, list(y.shape))


def broadcast_tensors(inputs, name=None):
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [expand(t, list(shape)) for t in inputs]


def _flip(x, *, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    return dispatch.apply("flip", _flip, (x,), {"axis": normalize_axis(axis)})


def _roll(x, *, shifts, axis):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    return dispatch.apply(
        "roll",
        _roll,
        (x,),
        {"shifts": static_int_list(shifts), "axis": normalize_axis(axis)},
    )


def _repeat_interleave(x, repeats, *, axis):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return dispatch.apply(
            "repeat_interleave",
            _repeat_interleave,
            (x, repeats),
            {"axis": normalize_axis(axis)},
            cache=False,
        )
    return dispatch.apply(
        "repeat_interleave",
        lambda xv, axis: jnp.repeat(xv, repeats, axis=axis),
        (x,),
        {"axis": normalize_axis(axis)},
        cache=False,
    )


# ------------------------------------------------------------------ triangular


def _tril(x, *, k):
    return jnp.tril(x, k)


def tril(x, diagonal=0, name=None):
    return dispatch.apply("tril", _tril, (x,), {"k": int(diagonal)})


def _triu(x, *, k):
    return jnp.triu(x, k)


def triu(x, diagonal=0, name=None):
    return dispatch.apply("triu", _triu, (x,), {"k": int(diagonal)})


def _diag(x, *, offset):
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0 and getattr(x, "ndim", 1) == 1:

        def _diag_pad(xv, *, offset):
            base = jnp.full(
                (xv.shape[0] + builtins.abs(offset),) * 2,
                padding_value,
                dtype=xv.dtype,
            )
            return base + jnp.diag(xv, k=offset) - jnp.diag(
                jnp.full((xv.shape[0],), padding_value, xv.dtype), k=offset
            )

        return dispatch.apply(
            "diag_pad", _diag_pad, (x,), {"offset": int(offset)}, cache=False
        )
    return dispatch.apply("diag", _diag, (x,), {"offset": int(offset)})


def _diagonal(x, *, offset, axis1, axis2):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch.apply(
        "diagonal",
        _diagonal,
        (x,),
        {"offset": int(offset), "axis1": int(axis1), "axis2": int(axis2)},
    )


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def _diag_embed(xv, *, offset):
        return jax.vmap(lambda v: jnp.diag(v, k=offset))(
            xv.reshape(-1, xv.shape[-1])
        ).reshape(xv.shape[:-1] + (xv.shape[-1] + builtins.abs(offset),) * 2)

    return dispatch.apply(
        "diag_embed", _diag_embed, (input,), {"offset": int(offset)}, cache=False
    )


# ------------------------------------------------------------------- indexing


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx.value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, builtins.slice):
        return builtins.slice(
            _unwrap_index(idx.start), _unwrap_index(idx.stop), _unwrap_index(idx.step)
        )
    return idx


def getitem(x, idx):
    idx_u = _unwrap_index(idx)

    def _get(xv):
        return xv[idx_u]

    return dispatch.apply("getitem", _get, (x,), cache=False)


def setitem(x, idx, v):
    idx_u = _unwrap_index(idx)

    def _set(xv, vv):
        return xv.at[idx_u].set(vv)

    if not isinstance(v, Tensor):
        v = Tensor(jnp.asarray(v, x.value.dtype))
    return dispatch.apply("setitem", _set, (x, v), cache=False)


def slice(input, axes, starts, ends, name=None):
    idx = [builtins.slice(None)] * input.ndim
    for ax, s, e in zip(static_int_list(axes), static_int_list(starts), static_int_list(ends)):
        idx[ax] = builtins.slice(s, e)
    return getitem(input, tuple(idx))


def strided_slice(x, axes, starts, ends, strides, name=None):
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(
        static_int_list(axes),
        static_int_list(starts),
        static_int_list(ends),
        static_int_list(strides),
    ):
        idx[ax] = builtins.slice(s, e, st)
    return getitem(x, tuple(idx))


def _gather(x, index, *, axis):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return dispatch.apply("gather", _gather, (x, index), {"axis": int(axis)})


def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return dispatch.apply("gather_nd", _gather_nd, (x, index))


def _index_select(x, index, *, axis):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return dispatch.apply(
        "index_select", _index_select, (x, index), {"axis": int(axis)}
    )


def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index):
    return dispatch.apply("index_sample", _index_sample, (x, index))


def _take_along_axis(x, indices, *, axis, broadcast):
    if broadcast:
        shape = list(x.shape)
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, shape)
    return jnp.take_along_axis(x, indices, axis=axis)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return dispatch.apply(
        "take_along_axis",
        _take_along_axis,
        (arr, indices),
        {"axis": int(axis), "broadcast": bool(broadcast)},
    )


def _put_along_axis(x, indices, values, *, axis, reduce):
    values = jnp.broadcast_to(values, indices.shape).astype(x.dtype)
    dims = list(range(x.ndim))
    idx = tuple(
        indices if d == axis else jnp.arange(x.shape[d]).reshape(
            [-1 if i == d else 1 for i in dims]
        )
        for d in dims
    )
    if reduce == "assign":
        return x.at[idx].set(values)
    if reduce == "add":
        return x.at[idx].add(values)
    if reduce == "multiply" or reduce == "mul":
        return x.at[idx].multiply(values)
    raise ValueError(f"unsupported reduce={reduce}")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    if not isinstance(values, Tensor):
        values = Tensor(jnp.asarray(values))
    return dispatch.apply(
        "put_along_axis",
        _put_along_axis,
        (arr, indices, values),
        {"axis": int(axis), "reduce": reduce},
    )


def _scatter(x, index, updates, *, overwrite):
    if overwrite:
        return x.at[index].set(updates)
    # paddle scatter(overwrite=False) accumulates after zeroing target rows
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return dispatch.apply(
        "scatter", _scatter, (x, index, updates), {"overwrite": bool(overwrite)}
    )


def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return dispatch.apply("scatter_nd_add", _scatter_nd_add, (x, index, updates))


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    base = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(base, index, updates)


def _masked_fill(x, mask, v):
    return jnp.where(mask, jnp.asarray(v, x.dtype), x)


def masked_fill(x, mask, value, name=None):
    return dispatch.apply("masked_fill", _masked_fill, (x, mask, value))


def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return nonzero(condition, as_tuple=True)
    return dispatch.apply("where", _where, (condition, x, y))


# --------------------------------------------------- dynamic-shape (eager-only)


def _require_eager(name):
    from ..core import tape

    if tape.in_trace():
        raise RuntimeError(
            f"{name} produces a data-dependent shape and cannot run inside a "
            "jit trace on TPU; compute it eagerly or use a fixed-size variant."
        )


def masked_select(x, mask, name=None):
    _require_eager("masked_select")

    def _ms(xv, mv):
        return xv[mv]

    return dispatch.apply("masked_select", _ms, (x, mask), cache=False)


def unique(
    x,
    return_index=False,
    return_inverse=False,
    return_counts=False,
    axis=None,
    dtype="int64",
    name=None,
):
    _require_eager("unique")
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    res = jnp.unique(
        xv,
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    _require_eager("unique_consecutive")
    xv = np.asarray(x.value if isinstance(x, Tensor) else x)
    if axis is None:
        xv = xv.reshape(-1)
    keep = np.ones(xv.shape[0], dtype=bool)
    keep[1:] = np.any(
        xv[1:].reshape(xv.shape[0] - 1, -1) != xv[:-1].reshape(xv.shape[0] - 1, -1),
        axis=1,
    )
    out = Tensor(jnp.asarray(xv[keep]))
    results = [out]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, xv.shape[0]))
        results.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return results[0] if len(results) == 1 else tuple(results)


# ------------------------------------------------------------------------ pad


def _pad_nd(x, *, paddings, mode, value):
    if mode == "constant":
        return jnp.pad(x, paddings, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, paddings, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad-compatible. ``pad`` is paddle layout:
    either len==2*ndim (per-dim lo/hi, dim0 first) or the common case of
    len==2*k applying to the last k spatial dims (NCHW/NCL/NCDHW)."""
    pad = static_int_list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        k = len(pad) // 2
        pairs = [(0, 0)] * nd
        if data_format.startswith("NC"):
            # pad covers the last k dims, ordered innermost-first (paddle)
            for i in range(k):
                dim = nd - 1 - i
                pairs[dim] = (pad[2 * i], pad[2 * i + 1])
        else:  # NHWC-style: spatial dims are 1..k
            for i in range(k):
                dim = 1 + (k - 1 - i)
                pairs[dim] = (pad[2 * i], pad[2 * i + 1])
    return dispatch.apply(
        "pad",
        _pad_nd,
        (x,),
        {"paddings": tuple(pairs), "mode": mode, "value": float(value)},
    )


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def clone(x, name=None):
    return assign(x)


def _as_complex(v):
    return jax.lax.complex(v[..., 0], v[..., 1])


def _as_real(v):
    return jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1)


def as_complex(x, name=None):
    return dispatch.apply("as_complex", _as_complex, (x,))


def as_real(x, name=None):
    return dispatch.apply("as_real", _as_real, (x,))


def _complex(re, im):
    return jax.lax.complex(re, im)


def complex(real, imag, name=None):
    """Construct a complex tensor from real and imaginary parts."""
    return dispatch.apply("complex", _complex, (real, imag))


def _add_n(*vs):
    out = vs[0]
    for v in vs[1:]:
        out = out + v
    return out


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (paddle.add_n)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if not inputs:
        raise ValueError("add_n: inputs must be a non-empty list")
    return dispatch.apply("add_n", _add_n, tuple(inputs), cache=False)
