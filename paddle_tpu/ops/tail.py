"""Remaining long-tail tensor ops: view/scatter surgery, split family,
special functions (reference parity: python/paddle/tensor/{manipulation,
math}.py rows — unverified, mount empty).

Same contract as ops/extras.py: each op is one pure jnp function routed
through core.dispatch (per-op jit + vjp autograd; fused inside whole-step
jit). All jax fns are module-level (stable identity) so dispatch's
fn-keyed jit cache hits across calls. View-like ops (``view``/
``as_strided``) are gathers on TPU — XLA has no aliasing views across jit
boundaries, so semantics are value-level.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ._helpers import binary, static_int_list, unary

# ----------------------------------------------------------- elementwise
copysign = binary("copysign", jnp.copysign)
gammaln = unary("gammaln", lambda x: jax.scipy.special.gammaln(x))
gammainc = binary("gammainc", lambda a, x: jax.scipy.special.gammainc(a, x))
gammaincc = binary("gammaincc", lambda a, x: jax.scipy.special.gammaincc(a, x))
isreal = unary("isreal", jnp.isreal, nondiff=True)
positive = unary("positive", jnp.positive)
negative = unary("negative", jnp.negative)


def _vecdot(xv, yv, *, axis):
    return jnp.sum(jnp.conj(xv) * yv, axis=axis)


def vecdot(x, y, axis=-1, name=None):
    return dispatch.apply("vecdot", _vecdot, (x, y), {"axis": int(axis)})


def _reduce_as(xv, *, axes, ts):
    out = jnp.sum(xv, axis=axes) if axes else xv
    return out.reshape(ts)


def reduce_as(x, target, name=None):
    """Sum ``x`` down to ``target``'s shape (the broadcast adjoint)."""
    xs, ts = tuple(x.shape), tuple(target.shape)
    lead = len(xs) - len(ts)
    axes = tuple(range(lead)) + tuple(
        lead + i for i, t in enumerate(ts) if t == 1 and xs[lead + i] != 1
    )
    return dispatch.apply(
        "reduce_as", _reduce_as, (x,), {"axes": axes, "ts": ts}
    )


# ------------------------------------------------------- view-like ops
def _view_dtype(xv, *, dt):
    return xv.view(dt)


def view(x, shape_or_dtype, name=None):
    """Value-level view: reshape, or dtype reinterpretation (bitcast)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        from .manipulation import reshape

        return reshape(x, shape_or_dtype)
    from ..core.dtypes import convert_dtype

    dt = jnp.dtype(convert_dtype(shape_or_dtype))
    return dispatch.apply(
        "view_dtype", _view_dtype, (x,), {"dt": dt}, nondiff=True
    )


def view_as(x, other, name=None):
    from .manipulation import reshape

    return reshape(x, list(other.shape))


def _as_strided(xv, *, shape, stride, offset):
    flat = xv.reshape(-1)
    idx = jnp.asarray(offset, jnp.int32)
    for n, s in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(n, dtype=jnp.int32) * s
    return flat[idx.reshape(shape)]


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided gather over x's contiguous flat buffer.

    TPU/XLA has no aliasing views; this materialises the strided window
    as a gather (differentiable via scatter-add in the vjp).
    """
    shape = static_int_list(shape)
    stride = static_int_list(stride)
    if isinstance(shape, int):
        shape = (shape,)
    if isinstance(stride, int):
        stride = (stride,)
    if len(shape) != len(stride):
        raise ValueError(
            "as_strided: shape and stride must have equal length, got "
            f"{shape} vs {stride}"
        )
    size = 1
    for d in x.shape:
        size *= int(d)
    lo = hi = int(offset)
    for n, s in zip(shape, stride):
        span = (n - 1) * s
        lo, hi = lo + min(0, span), hi + max(0, span)
    if shape and (lo < 0 or hi >= size):
        raise ValueError(
            f"as_strided: window [{lo}, {hi}] out of bounds for tensor of "
            f"{size} elements (shape={shape}, stride={stride}, "
            f"offset={offset})"
        )
    return dispatch.apply(
        "as_strided", _as_strided, (x,),
        {"shape": shape, "stride": stride, "offset": int(offset)},
    )


def _crop(xv, *, offsets, shape):
    return jax.lax.slice(xv, offsets, [o + s for o, s in zip(offsets, shape)])


def crop(x, shape=None, offsets=None, name=None):
    nd = len(x.shape)
    shape = list(static_int_list(shape)) if shape is not None else list(x.shape)
    offsets = (
        list(static_int_list(offsets)) if offsets is not None else [0] * nd
    )
    # -1 in shape: take everything from the offset to the end of that dim
    for i in range(nd):
        if shape[i] == -1:
            shape[i] = int(x.shape[i]) - offsets[i]
    return dispatch.apply(
        "crop", _crop, (x,), {"offsets": tuple(offsets), "shape": tuple(shape)}
    )


# ------------------------------------------------------ scatter surgery
def _select_scatter(xv, vv, *, axis, index):
    moved = jnp.moveaxis(xv, axis, 0)
    moved = moved.at[index].set(vv.astype(xv.dtype))
    return jnp.moveaxis(moved, 0, axis)


def select_scatter(x, values, axis, index, name=None):
    axis = int(axis) % len(x.shape)
    index = int(index) % int(x.shape[axis])
    return dispatch.apply(
        "select_scatter", _select_scatter, (x, values),
        {"axis": axis, "index": index},
    )


def _diagonal_scatter(xv, yv, *, offset, axis1, axis2):
    moved = jnp.moveaxis(xv, (axis1, axis2), (-2, -1))
    m, n = moved.shape[-2], moved.shape[-1]
    if offset >= 0:
        length = min(m, n - offset)
        rows = jnp.arange(length)
        cols = rows + offset
    else:
        length = min(m + offset, n)
        rows = jnp.arange(length) - offset
        cols = jnp.arange(length)
    moved = moved.at[..., rows, cols].set(yv.astype(xv.dtype))
    return jnp.moveaxis(moved, (-2, -1), (axis1, axis2))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    nd = len(x.shape)
    return dispatch.apply(
        "diagonal_scatter", _diagonal_scatter, (x, y),
        {"offset": int(offset), "axis1": int(axis1) % nd,
         "axis2": int(axis2) % nd},
    )


# --------------------------------------------------------- split family
def _tensor_split(xv, *, starts, sizes, axis):
    return tuple(
        jax.lax.slice_in_dim(xv, st, st + sz, axis=axis)
        for st, sz in zip(starts, sizes)
    )


def tensor_split(x, num_or_indices, axis=0, name=None):
    axis = int(axis) % len(x.shape)
    dim = int(x.shape[axis])
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + 1] * extra + [base] * (n - extra)
        starts = []
        s = 0
        for sz in sizes:
            starts.append(s)
            s += sz
    else:
        pts = [int(p) for p in static_int_list(num_or_indices)]
        # numpy semantics: negative indices wrap, out-of-range clamps,
        # reversed pairs produce empty segments at the clamped start
        pts = [min(max(p + dim if p < 0 else p, 0), dim) for p in pts]
        bounds = [0] + pts + [dim]
        starts = bounds[:-1]
        sizes = [max(0, b - a) for a, b in zip(bounds[:-1], bounds[1:])]
    out = dispatch.apply(
        "tensor_split", _tensor_split, (x,),
        {"starts": tuple(starts), "sizes": tuple(sizes), "axis": axis},
    )
    return list(out)


def hsplit(x, num_or_indices, name=None):
    if len(x.shape) < 1:
        raise ValueError("hsplit expects at least a 1-D tensor")
    return tensor_split(x, num_or_indices, axis=0 if len(x.shape) == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    if len(x.shape) < 2:
        raise ValueError("vsplit expects at least a 2-D tensor")
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    if len(x.shape) < 3:
        raise ValueError("dsplit expects at least a 3-D tensor")
    return tensor_split(x, num_or_indices, axis=2)
