"""Remaining long-tail tensor ops: view/scatter surgery, split family,
special functions (reference parity: python/paddle/tensor/{manipulation,
math}.py rows — unverified, mount empty).

Same contract as ops/extras.py: each op is one pure jnp function routed
through core.dispatch (per-op jit + vjp autograd; fused inside whole-step
jit). All jax fns are module-level (stable identity) so dispatch's
fn-keyed jit cache hits across calls. View-like ops (``view``/
``as_strided``) are gathers on TPU — XLA has no aliasing views across jit
boundaries, so semantics are value-level.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ._helpers import binary, static_int_list, unary

# ----------------------------------------------------------- elementwise
copysign = binary("copysign", jnp.copysign)
gammaln = unary("gammaln", lambda x: jax.scipy.special.gammaln(x))
gammainc = binary("gammainc", lambda a, x: jax.scipy.special.gammainc(a, x))
gammaincc = binary("gammaincc", lambda a, x: jax.scipy.special.gammaincc(a, x))
isreal = unary("isreal", jnp.isreal, nondiff=True)
positive = unary("positive", jnp.positive)
negative = unary("negative", jnp.negative)


def _vecdot(xv, yv, *, axis):
    return jnp.sum(jnp.conj(xv) * yv, axis=axis)


def vecdot(x, y, axis=-1, name=None):
    return dispatch.apply("vecdot", _vecdot, (x, y), {"axis": int(axis)})


def _reduce_as(xv, *, axes, ts):
    out = jnp.sum(xv, axis=axes) if axes else xv
    return out.reshape(ts)


def reduce_as(x, target, name=None):
    """Sum ``x`` down to ``target``'s shape (the broadcast adjoint)."""
    xs, ts = tuple(x.shape), tuple(target.shape)
    lead = len(xs) - len(ts)
    axes = tuple(range(lead)) + tuple(
        lead + i for i, t in enumerate(ts) if t == 1 and xs[lead + i] != 1
    )
    return dispatch.apply(
        "reduce_as", _reduce_as, (x,), {"axes": axes, "ts": ts}
    )


# ------------------------------------------------------- view-like ops
def _view_dtype(xv, *, dt):
    return xv.view(dt)


def view(x, shape_or_dtype, name=None):
    """Value-level view: reshape, or dtype reinterpretation (bitcast)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        from .manipulation import reshape

        return reshape(x, shape_or_dtype)
    from ..core.dtypes import convert_dtype

    dt = jnp.dtype(convert_dtype(shape_or_dtype))
    return dispatch.apply(
        "view_dtype", _view_dtype, (x,), {"dt": dt}, nondiff=True
    )


def view_as(x, other, name=None):
    from .manipulation import reshape

    return reshape(x, list(other.shape))


def _as_strided(xv, *, shape, stride, offset):
    flat = xv.reshape(-1)
    idx = jnp.asarray(offset, jnp.int32)
    for n, s in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(n, dtype=jnp.int32) * s
    return flat[idx.reshape(shape)]


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided gather over x's contiguous flat buffer.

    TPU/XLA has no aliasing views; this materialises the strided window
    as a gather (differentiable via scatter-add in the vjp).
    """
    shape = static_int_list(shape)
    stride = static_int_list(stride)
    if isinstance(shape, int):
        shape = (shape,)
    if isinstance(stride, int):
        stride = (stride,)
    if len(shape) != len(stride):
        raise ValueError(
            "as_strided: shape and stride must have equal length, got "
            f"{shape} vs {stride}"
        )
    size = 1
    for d in x.shape:
        size *= int(d)
    lo = hi = int(offset)
    for n, s in zip(shape, stride):
        span = (n - 1) * s
        lo, hi = lo + min(0, span), hi + max(0, span)
    if shape and (lo < 0 or hi >= size):
        raise ValueError(
            f"as_strided: window [{lo}, {hi}] out of bounds for tensor of "
            f"{size} elements (shape={shape}, stride={stride}, "
            f"offset={offset})"
        )
    return dispatch.apply(
        "as_strided", _as_strided, (x,),
        {"shape": shape, "stride": stride, "offset": int(offset)},
    )


def _crop(xv, *, offsets, shape):
    return jax.lax.slice(xv, offsets, [o + s for o, s in zip(offsets, shape)])


def crop(x, shape=None, offsets=None, name=None):
    nd = len(x.shape)
    shape = list(static_int_list(shape)) if shape is not None else list(x.shape)
    offsets = (
        list(static_int_list(offsets)) if offsets is not None else [0] * nd
    )
    # -1 in shape: take everything from the offset to the end of that dim
    for i in range(nd):
        if shape[i] == -1:
            shape[i] = int(x.shape[i]) - offsets[i]
    return dispatch.apply(
        "crop", _crop, (x,), {"offsets": tuple(offsets), "shape": tuple(shape)}
    )


# ------------------------------------------------------ scatter surgery
def _select_scatter(xv, vv, *, axis, index):
    moved = jnp.moveaxis(xv, axis, 0)
    moved = moved.at[index].set(vv.astype(xv.dtype))
    return jnp.moveaxis(moved, 0, axis)


def select_scatter(x, values, axis, index, name=None):
    axis = int(axis) % len(x.shape)
    index = int(index) % int(x.shape[axis])
    return dispatch.apply(
        "select_scatter", _select_scatter, (x, values),
        {"axis": axis, "index": index},
    )


def _diagonal_scatter(xv, yv, *, offset, axis1, axis2):
    moved = jnp.moveaxis(xv, (axis1, axis2), (-2, -1))
    m, n = moved.shape[-2], moved.shape[-1]
    if offset >= 0:
        length = min(m, n - offset)
        rows = jnp.arange(length)
        cols = rows + offset
    else:
        length = min(m + offset, n)
        rows = jnp.arange(length) - offset
        cols = jnp.arange(length)
    moved = moved.at[..., rows, cols].set(yv.astype(xv.dtype))
    return jnp.moveaxis(moved, (-2, -1), (axis1, axis2))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    nd = len(x.shape)
    return dispatch.apply(
        "diagonal_scatter", _diagonal_scatter, (x, y),
        {"offset": int(offset), "axis1": int(axis1) % nd,
         "axis2": int(axis2) % nd},
    )


# --------------------------------------------------------- split family
def _tensor_split(xv, *, starts, sizes, axis):
    return tuple(
        jax.lax.slice_in_dim(xv, st, st + sz, axis=axis)
        for st, sz in zip(starts, sizes)
    )


def tensor_split(x, num_or_indices, axis=0, name=None):
    axis = int(axis) % len(x.shape)
    dim = int(x.shape[axis])
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + 1] * extra + [base] * (n - extra)
        starts = []
        s = 0
        for sz in sizes:
            starts.append(s)
            s += sz
    else:
        pts = [int(p) for p in static_int_list(num_or_indices)]
        # numpy semantics: negative indices wrap, out-of-range clamps,
        # reversed pairs produce empty segments at the clamped start
        pts = [min(max(p + dim if p < 0 else p, 0), dim) for p in pts]
        bounds = [0] + pts + [dim]
        starts = bounds[:-1]
        sizes = [max(0, b - a) for a, b in zip(bounds[:-1], bounds[1:])]
    out = dispatch.apply(
        "tensor_split", _tensor_split, (x,),
        {"starts": tuple(starts), "sizes": tuple(sizes), "axis": axis},
    )
    return list(out)


def hsplit(x, num_or_indices, name=None):
    if len(x.shape) < 1:
        raise ValueError("hsplit expects at least a 1-D tensor")
    return tensor_split(x, num_or_indices, axis=0 if len(x.shape) == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    if len(x.shape) < 2:
        raise ValueError("vsplit expects at least a 2-D tensor")
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    if len(x.shape) < 3:
        raise ValueError("dsplit expects at least a 3-D tensor")
    return tensor_split(x, num_or_indices, axis=2)


# ------------------------------------------------------ misc reference ops
def rank(input, name=None):
    """0-D int32 tensor holding ndim (reference paddle.rank)."""
    from .creation import to_tensor

    return to_tensor(len(input.shape), dtype="int32")


def _increment(x, *, v):
    return x + v


def _increment_out(x, value):
    return dispatch.apply("increment", _increment, (x,), {"v": float(value)})


def increment(x, value=1.0, name=None):
    """In-place like the reference (loop counters: paddle.increment(i)
    as a bare statement must advance i)."""
    return x._inplace(_increment_out, value)


def _shard_index(x, *, index_num, nshards, shard_id, ignore_value):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    in_shard = (x >= lo) & (x < lo + shard_size)
    return jnp.where(in_shard, x - lo, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for {nshards} shards"
        )
    return dispatch.apply(
        "shard_index", _shard_index, (input,),
        {"index_num": int(index_num), "nshards": int(nshards),
         "shard_id": int(shard_id), "ignore_value": int(ignore_value)},
    )


def _multiplex(index, *ins):
    stacked = jnp.stack(ins, axis=0)  # [K, B, ...]
    rows = jnp.arange(stacked.shape[1])
    return stacked[index[:, 0].astype(jnp.int32), rows]


def multiplex(inputs, index, name=None):
    """Row-wise select across candidate tensors (reference multiplex)."""
    return dispatch.apply(
        "multiplex", _multiplex, (index, *tuple(inputs))
    )


def _temporal_shift(x, *, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    xs = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    back = jnp.concatenate(
        [xs[:, 1:, :fold], jnp.zeros_like(xs[:, :1, :fold])], axis=1
    )
    fwd = jnp.concatenate(
        [jnp.zeros_like(xs[:, :1, fold:2 * fold]),
         xs[:, :-1, fold:2 * fold]], axis=1
    )
    keep = xs[:, :, 2 * fold:]
    return jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    if data_format != "NCHW":
        from .manipulation import transpose

        x = transpose(x, [0, 3, 1, 2])
    out = dispatch.apply(
        "temporal_shift", _temporal_shift, (x,),
        {"seg_num": int(seg_num), "shift_ratio": float(shift_ratio)},
    )
    if data_format != "NCHW":
        from .manipulation import transpose

        out = transpose(out, [0, 2, 3, 1])
    return out


def _addbmm(inp, x, y, *, beta, alpha):
    return beta * inp + alpha * jnp.sum(jnp.matmul(x, y), axis=0)


def addbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return dispatch.apply(
        "addbmm", _addbmm, (input, x, y),
        {"beta": float(beta), "alpha": float(alpha)},
    )


def _baddbmm(inp, x, y, *, beta, alpha):
    return beta * inp + alpha * jnp.matmul(x, y)


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return dispatch.apply(
        "baddbmm", _baddbmm, (input, x, y),
        {"beta": float(beta), "alpha": float(alpha)},
    )


def _hist_edges(x, *, bins, lo, hi):
    minv = jnp.min(x) if lo == hi == 0 else jnp.asarray(lo, x.dtype)
    maxv = jnp.max(x) if lo == hi == 0 else jnp.asarray(hi, x.dtype)
    # numpy degenerate-range convention: [v, v] -> [v-0.5, v+0.5]
    degen = maxv == minv
    minv = jnp.where(degen, minv - 0.5, minv)
    maxv = jnp.where(degen, maxv + 0.5, maxv)
    return jnp.linspace(minv, maxv, bins + 1).astype(x.dtype)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    return dispatch.apply(
        "histogram_bin_edges", _hist_edges, (input,),
        {"bins": int(bins), "lo": float(min), "hi": float(max)},
    )


def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x.value).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x.value).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x.value).dtype, jnp.integer)


def tolist(x):
    return x.tolist()


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    from ..core import random as random_mod
    from ..core.dtypes import convert_dtype, get_default_dtype

    dt = jnp.dtype(convert_dtype(dtype) or get_default_dtype())
    shp = tuple(int(s) for s in (shape or []))

    def _ln(*, key, mean, std, shp, dt):
        return jnp.exp(mean + std * jax.random.normal(key, shp, dt))

    return dispatch.apply(
        "log_normal", _ln, (),
        {"key": random_mod.next_key(), "mean": float(mean),
         "std": float(std), "shp": shp, "dt": dt},
        cache=False, nondiff=True,
    )


# ------------------------------------------------------------ segment ops
def _segment_reduce(x, ids, *, n, how):
    cnt = jnp.zeros((n,), jnp.int32).at[ids].add(1)
    empty = (cnt == 0).reshape((n,) + (1,) * (x.ndim - 1))
    zero = jnp.zeros((), x.dtype)
    if how == "sum" or how == "mean":
        out = jnp.zeros((n,) + x.shape[1:], x.dtype).at[ids].add(x)
        if how == "mean":
            denom = jnp.maximum(cnt, 1).astype(x.dtype).reshape(
                (n,) + (1,) * (x.ndim - 1)
            )
            out = out / denom
        return out
    # max/min: dtype-preserving sentinel init, empty segments -> 0
    # (reference contract); count-based masking keeps legitimate
    # +-inf values intact
    if how == "max":
        init_v = (
            jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min
        )
        out = jnp.full((n,) + x.shape[1:], init_v, x.dtype).at[ids].max(x)
    else:
        init_v = (
            jnp.finfo(x.dtype).max if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).max
        )
        out = jnp.full((n,) + x.shape[1:], init_v, x.dtype).at[ids].min(x)
    return jnp.where(empty, zero, out)


def _segment_n(segment_ids):
    ids = segment_ids
    return int(jnp.max(jnp.asarray(
        ids.value if isinstance(ids, Tensor) else ids
    ))) + 1


def _segment(name, how):
    def op(data, segment_ids, name=None):
        return dispatch.apply(
            f"segment_{how}", _segment_reduce, (data, segment_ids),
            {"n": _segment_n(segment_ids), "how": how},
        )

    op.__name__ = name
    return op


segment_sum = _segment("segment_sum", "sum")
segment_mean = _segment("segment_mean", "mean")
segment_max = _segment("segment_max", "max")
segment_min = _segment("segment_min", "min")
