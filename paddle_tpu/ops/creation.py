"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (unverified, mount
empty). Creation happens directly on the current Place's jax device.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core import device as device_mod
from ..core import random as random_mod
from ..core import tape as tape_mod
from ..core.dtypes import convert_dtype, get_default_dtype
from ..core.tensor import Tensor
from ._helpers import static_int_list


def _device():
    return device_mod.jax_device()


def _place(arr):
    """Put a freshly created array on the current device (eager only).

    Single-process SPMD: when a multi-device mesh is installed, the mesh
    IS the current device — eager arrays are placed mesh-replicated so
    they compose with mesh-placed params/optimizer state (ZeRO, TP)
    without per-op device juggling."""
    if tape_mod.in_trace():
        return arr
    s = _spmd_replicated_sharding()
    if s is not None:
        return jax.device_put(arr, s)
    return jax.device_put(arr, _device())


_REPL_CACHE = {"epoch": -1, "sharding": None}


def _spmd_replicated_sharding():
    """Replicated NamedSharding over the active mesh (cached per mesh
    epoch — this sits on the eager creation hot path), or None when no
    multi-device mesh is active / in a multi-process world."""
    from ..parallel import mesh as mesh_mod

    epoch = mesh_mod._STATE["epoch"]
    if _REPL_CACHE["epoch"] == epoch:
        return _REPL_CACHE["sharding"]
    sharding = None
    mesh = mesh_mod._STATE["mesh"]
    if mesh is not None and mesh.size > 1:
        from ..distributed.env import get_world_size

        if get_world_size() == 1:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, PartitionSpec())
    _REPL_CACHE["epoch"] = epoch
    _REPL_CACHE["sharding"] = sharding
    return sharding


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        out = Tensor(data.value, stop_gradient=stop_gradient)
        if dtype is not None:
            out = out.astype(dtype)
            out.stop_gradient = stop_gradient
        return out
    if dtype is None:
        arr = np.asarray(data)
        if arr.dtype == np.float64:
            arr = arr.astype(get_default_dtype())
        elif arr.dtype == np.int32:
            # paddle default integer dtype follows input; keep as-is
            pass
    else:
        arr = np.asarray(data, dtype=convert_dtype(dtype))
    return Tensor(_place(jnp.asarray(arr)), stop_gradient=stop_gradient)


def tensor(data, dtype=None, place=None, stop_gradient=True):
    return to_tensor(data, dtype, place, stop_gradient)


def zeros(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(_place(jnp.zeros(_shape(shape), d)))


def ones(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(_place(jnp.ones(_shape(shape), d)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = convert_dtype(dtype)
    if d is None:
        d = get_default_dtype() if isinstance(fill_value, float) else None
    arr = jnp.full(_shape(shape), fill_value, d)
    return Tensor(_place(arr))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    d = convert_dtype(dtype)
    return Tensor(_place(jnp.zeros_like(v, dtype=d)))


def ones_like(x, dtype=None, name=None):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    d = convert_dtype(dtype)
    return Tensor(_place(jnp.ones_like(v, dtype=d)))


def full_like(x, fill_value, dtype=None, name=None):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    d = convert_dtype(dtype)
    return Tensor(_place(jnp.full_like(v, fill_value, dtype=d)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            d = get_default_dtype()
        else:
            d = np.dtype("int64")
    return Tensor(_place(jnp.arange(start, end, step, dtype=d)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(_place(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=d)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(_place(jnp.logspace(start, stop, int(num), base=base, dtype=d)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(_place(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=d)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    vals = [t.value if isinstance(t, Tensor) else jnp.asarray(t) for t in tensors]
    outs = jnp.meshgrid(*vals, indexing="ij")
    return [Tensor(o) for o in outs]


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(_place(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype))))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(_place(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype))))


def _one_hot(xv, *, n):
    return jax.nn.one_hot(xv, n, dtype=get_default_dtype())


def one_hot(x, num_classes, name=None):
    from ..core import dispatch

    return dispatch.apply("one_hot", _one_hot, (x,), {"n": int(num_classes)})


def clone(x, name=None):
    from .manipulation import assign

    return assign(x)


# ----------------------------------------------------------------- random


def rand(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    key = random_mod.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=d))


def randn(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    key = random_mod.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=d))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = random_mod.next_key()
    return Tensor(
        jax.random.randint(key, _shape(shape), int(low), int(high)).astype(
            convert_dtype(dtype)
        )
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    d = convert_dtype(dtype) or x.dtype
    return randint(low, high, tuple(x.shape), d)


def randperm(n, dtype="int64", name=None):
    key = random_mod.next_key()
    return Tensor(jax.random.permutation(key, int(n)).astype(convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    key = jax.random.key(seed) if seed else random_mod.next_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), dtype=d, minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        mv = mean.value if isinstance(mean, Tensor) else mean
        sv = std.value if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            jnp.shape(mv), jnp.shape(sv)
        )
        key = random_mod.next_key()
        return Tensor(
            jax.random.normal(key, out_shape, dtype=get_default_dtype()) * sv + mv
        )
    key = random_mod.next_key()
    return Tensor(
        jax.random.normal(key, _shape(shape), dtype=get_default_dtype()) * std + mean
    )


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def bernoulli(x, name=None):
    key = random_mod.next_key()
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(key, xv).astype(xv.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = random_mod.next_key()
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if xv.ndim == 1:
        out = jax.random.choice(
            key,
            xv.shape[0],
            shape=(int(num_samples),),
            replace=bool(replacement),
            p=xv / xv.sum(),
        )
    else:
        keys = jax.random.split(key, xv.shape[0])
        out = jnp.stack(
            [
                jax.random.choice(
                    k,
                    xv.shape[1],
                    shape=(int(num_samples),),
                    replace=bool(replacement),
                    p=row / row.sum(),
                )
                for k, row in zip(keys, xv)
            ]
        )
    return Tensor(out.astype(jnp.int64))
