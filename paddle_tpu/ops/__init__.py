"""The flat op namespace: everything paddle exposes at tensor level.

Aggregates creation/math/reduction/manipulation/search/logic/linalg into one
namespace consumed by paddle_tpu/__init__.py (as ``paddle_tpu.<op>``) and
bound as Tensor methods. Reference parity: python/paddle/tensor/__init__.py
(unverified, mount empty).
"""
from __future__ import annotations

from . import (
    creation,
    extras,
    inplace,
    linalg,
    logic,
    manipulation,
    math,
    reduction,
    search,
    tail,
)

_MODULES = [creation, math, reduction, manipulation, search, logic, linalg,
            extras, tail, inplace]

# helper/infra names that are callable but are NOT ops
_EXCLUDE = {
    "unary",
    "binary",
    "normalize_axis",
    "static_int_list",
    "convert_dtype",
    "get_default_dtype",
    "Tensor",
    "Parameter",
}

__all__ = []


def _export(mod):
    for name, obj in vars(mod).items():
        if name.startswith("_") or name in _EXCLUDE:
            continue
        if not callable(obj):
            continue
        if not getattr(obj, "__module__", "").startswith("paddle_tpu"):
            continue  # raw jnp/np functions leaked via direct assignment
        globals().setdefault(name, obj)
        if name not in __all__:
            __all__.append(name)


for _m in _MODULES:
    _export(_m)
