"""Reduction ops (paddle semantics: ``axis``/``keepdim``).

Reference parity: python/paddle/tensor/math.py reductions + phi reduce
kernels (reference: paddle/phi/kernels/gpu/reduce_*.cu — unverified, mount
empty); on TPU, XLA lowers these straight to efficient tree reductions.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dispatch
from ._helpers import normalize_axis


def _make_reduce(name, jfn):
    def fn(x, *, axis, keepdim):
        return jfn(x, axis=axis, keepdims=keepdim)

    fn.__name__ = "_" + name

    def op(x, axis=None, keepdim=False, name=None):
        return dispatch.apply(
            op_name, fn, (x,), {"axis": normalize_axis(axis), "keepdim": bool(keepdim)}
        )

    op_name = name
    op.__name__ = name
    return op


sum = _make_reduce("sum", jnp.sum)
mean = _make_reduce("mean", jnp.mean)
prod = _make_reduce("prod", jnp.prod)
max = _make_reduce("max", jnp.max)
min = _make_reduce("min", jnp.min)
amax = _make_reduce("amax", jnp.max)
amin = _make_reduce("amin", jnp.min)
all = _make_reduce("all", jnp.all)
any = _make_reduce("any", jnp.any)
nanmean = _make_reduce("nanmean", jnp.nanmean)
nansum = _make_reduce("nansum", jnp.nansum)
median = _make_reduce("median", jnp.median)
nanmedian = _make_reduce("nanmedian", jnp.nanmedian)


def _std(x, *, axis, keepdim, unbiased):
    return jnp.std(x, axis=axis, keepdims=keepdim, ddof=1 if unbiased else 0)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return dispatch.apply(
        "std",
        _std,
        (x,),
        {
            "axis": normalize_axis(axis),
            "keepdim": bool(keepdim),
            "unbiased": bool(unbiased),
        },
    )


def _var(x, *, axis, keepdim, unbiased):
    return jnp.var(x, axis=axis, keepdims=keepdim, ddof=1 if unbiased else 0)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return dispatch.apply(
        "var",
        _var,
        (x,),
        {
            "axis": normalize_axis(axis),
            "keepdim": bool(keepdim),
            "unbiased": bool(unbiased),
        },
    )


def _logsumexp(x, *, axis, keepdim):
    from jax.scipy.special import logsumexp as lse

    return lse(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return dispatch.apply(
        "logsumexp",
        _logsumexp,
        (x,),
        {"axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )


def _count_nonzero(x, *, axis, keepdim):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return dispatch.apply(
        "count_nonzero",
        _count_nonzero,
        (x,),
        {"axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )


def _quantile(x, q, *, axis, keepdim):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return dispatch.apply(
        "quantile",
        _quantile,
        (x, q),
        {"axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )
