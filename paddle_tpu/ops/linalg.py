"""Linear algebra ops (paddle.linalg parity).

Reference parity: python/paddle/tensor/linalg.py (unverified, mount empty).
Decompositions route to jnp.linalg — XLA implements these natively; on TPU
they run through the MXU where applicable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ._helpers import normalize_axis

from .math import matmul, mm, bmm, dot, outer, inner  # re-export  # noqa: F401


def _norm(x, *, p, axis, keepdim):
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None else 2
    return dispatch.apply(
        "norm",
        _norm,
        (x,),
        {"p": p, "axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )


vector_norm = norm


def _matrix_norm(x, *, p, keepdim):
    return jnp.linalg.norm(x, ord=p, axis=(-2, -1), keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return dispatch.apply(
        "matrix_norm", _matrix_norm, (x,), {"p": p, "keepdim": bool(keepdim)}
    )


def _det(x):
    return jnp.linalg.det(x)


def det(x, name=None):
    return dispatch.apply("det", _det, (x,))


def _slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


def slogdet(x, name=None):
    return dispatch.apply("slogdet", _slogdet, (x,))


def _inv(x):
    return jnp.linalg.inv(x)


def inv(x, name=None):
    return dispatch.apply("inv", _inv, (x,))


def _pinv(x, *, rcond):
    return jnp.linalg.pinv(x, rtol=rcond)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return dispatch.apply("pinv", _pinv, (x,), {"rcond": float(rcond)})


def _solve(a, b):
    return jnp.linalg.solve(a, b)


def solve(x, y, name=None):
    return dispatch.apply("solve", _solve, (x, y))


def _triangular_solve(a, b, *, upper, transpose, unitriangular):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular,
    )


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return dispatch.apply(
        "triangular_solve",
        _triangular_solve,
        (x, y),
        {
            "upper": bool(upper),
            "transpose": bool(transpose),
            "unitriangular": bool(unitriangular),
        },
    )


def _cholesky(x, *, upper):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return dispatch.apply("cholesky", _cholesky, (x,), {"upper": bool(upper)})


def _cholesky_solve(b, l, *, upper):
    a = jnp.matmul(l, jnp.swapaxes(l, -1, -2)) if not upper else jnp.matmul(
        jnp.swapaxes(l, -1, -2), l
    )
    return jnp.linalg.solve(a, b)


def cholesky_solve(x, y, upper=False, name=None):
    return dispatch.apply(
        "cholesky_solve", _cholesky_solve, (x, y), {"upper": bool(upper)}
    )


def _qr(x, *, mode):
    return tuple(jnp.linalg.qr(x, mode=mode))


def qr(x, mode="reduced", name=None):
    out = dispatch.apply("qr", _qr, (x,), {"mode": mode})
    return out[0], out[1]


def _svd(x, *, full_matrices):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def svd(x, full_matrices=False, name=None):
    out = dispatch.apply("svd", _svd, (x,), {"full_matrices": bool(full_matrices)})
    return out[0], out[1], out[2]


def _eigh(x, *, uplo):
    return tuple(jnp.linalg.eigh(x, UPLO=uplo))


def eigh(x, UPLO="L", name=None):
    out = dispatch.apply("eigh", _eigh, (x,), {"uplo": UPLO})
    return out[0], out[1]


def _eigvalsh(x, *, uplo):
    return jnp.linalg.eigvalsh(x, UPLO=uplo)


def eigvalsh(x, UPLO="L", name=None):
    return dispatch.apply("eigvalsh", _eigvalsh, (x,), {"uplo": UPLO})


def _eig(x):
    return tuple(jnp.linalg.eig(x))


def eig(x, name=None):
    # CPU-only in XLA; fine for the eager/debug path
    out = dispatch.apply("eig", _eig, (x,), cache=False)
    return out[0], out[1]


def _matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return dispatch.apply("matrix_power", _matrix_power, (x,), {"n": int(n)})


def _matrix_rank(x, *, tol):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return dispatch.apply("matrix_rank", _matrix_rank, (x,), {"tol": tol})


def _lstsq(a, b, *, rcond):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res, rank, sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    out = dispatch.apply("lstsq", _lstsq, (x, y), {"rcond": rcond})
    return tuple(out)


def _cond(x, *, p):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return dispatch.apply("cond", _cond, (x,), {"p": p})


def _lu(x):
    import jax.scipy.linalg as jsl

    lu_mat, piv = jsl.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32)


def lu(x, pivot=True, get_infos=False, name=None):
    out = dispatch.apply("lu", _lu, (x,))
    if get_infos:
        from .creation import zeros

        return out[0], out[1], zeros([1], dtype="int32")
    return out[0], out[1]


def _einsum(*operands, equation):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return dispatch.apply("einsum", _einsum, tuple(operands), {"equation": equation})


def _multi_dot(*mats):
    return jnp.linalg.multi_dot(mats)


def multi_dot(x, name=None):
    return dispatch.apply("multi_dot", _multi_dot, tuple(x))


def _householder_product(a, tau):
    # form Q from householder reflectors (geqrf layout)
    m, n = a.shape[-2], a.shape[-1]
    q = jnp.eye(m, dtype=a.dtype)
    q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q

    def body(i, q):
        v = jnp.where(jnp.arange(m) > i, a[..., :, i], 0.0)
        v = v.at[..., i].set(1.0)
        t = tau[..., i]
        vvt = jnp.einsum("...i,...j->...ij", v, v)
        h = jnp.eye(m, dtype=a.dtype) - t[..., None, None] * vvt
        return jnp.matmul(q, h)

    q = jax.lax.fori_loop(0, n, body, q)
    return q[..., :, :n]


def householder_product(x, tau, name=None):
    return dispatch.apply("householder_product", _householder_product, (x, tau))


def _eigvals(a):
    return jnp.linalg.eigvals(a)


def eigvals(x, name=None):
    return dispatch.apply("eigvals", _eigvals, (x,), nondiff=True)


def _svdvals(a):
    return jnp.linalg.svdvals(a)


def svdvals(x, name=None):
    return dispatch.apply("svdvals", _svdvals, (x,))
