"""Linear algebra ops (paddle.linalg parity).

Reference parity: python/paddle/tensor/linalg.py (unverified, mount empty).
Decompositions route to jnp.linalg — XLA implements these natively; on TPU
they run through the MXU where applicable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ._helpers import normalize_axis

from .math import matmul, mm, bmm, dot, outer, inner  # re-export  # noqa: F401


def _norm(x, *, p, axis, keepdim):
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None else 2
    return dispatch.apply(
        "norm",
        _norm,
        (x,),
        {"p": p, "axis": normalize_axis(axis), "keepdim": bool(keepdim)},
    )


vector_norm = norm


def _matrix_norm(x, *, p, keepdim):
    return jnp.linalg.norm(x, ord=p, axis=(-2, -1), keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return dispatch.apply(
        "matrix_norm", _matrix_norm, (x,), {"p": p, "keepdim": bool(keepdim)}
    )


def _det(x):
    return jnp.linalg.det(x)


def det(x, name=None):
    return dispatch.apply("det", _det, (x,))


def _slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


def slogdet(x, name=None):
    return dispatch.apply("slogdet", _slogdet, (x,))


def _inv(x):
    return jnp.linalg.inv(x)


def inverse(x, name=None):
    """Alias of ``inv`` (paddle exposes both)."""
    return inv(x)


def inv(x, name=None):
    return dispatch.apply("inv", _inv, (x,))


def _pinv(x, *, rcond):
    return jnp.linalg.pinv(x, rtol=rcond)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return dispatch.apply("pinv", _pinv, (x,), {"rcond": float(rcond)})


def _solve(a, b):
    return jnp.linalg.solve(a, b)


def solve(x, y, name=None):
    return dispatch.apply("solve", _solve, (x, y))


def _triangular_solve(a, b, *, upper, transpose, unitriangular):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular,
    )


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return dispatch.apply(
        "triangular_solve",
        _triangular_solve,
        (x, y),
        {
            "upper": bool(upper),
            "transpose": bool(transpose),
            "unitriangular": bool(unitriangular),
        },
    )


def _cholesky(x, *, upper):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return dispatch.apply("cholesky", _cholesky, (x,), {"upper": bool(upper)})


def _cholesky_solve(b, l, *, upper):
    a = jnp.matmul(l, jnp.swapaxes(l, -1, -2)) if not upper else jnp.matmul(
        jnp.swapaxes(l, -1, -2), l
    )
    return jnp.linalg.solve(a, b)


def cholesky_solve(x, y, upper=False, name=None):
    return dispatch.apply(
        "cholesky_solve", _cholesky_solve, (x, y), {"upper": bool(upper)}
    )


def _qr(x, *, mode):
    return tuple(jnp.linalg.qr(x, mode=mode))


def qr(x, mode="reduced", name=None):
    out = dispatch.apply("qr", _qr, (x,), {"mode": mode})
    return out[0], out[1]


def _svd(x, *, full_matrices):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def svd(x, full_matrices=False, name=None):
    out = dispatch.apply("svd", _svd, (x,), {"full_matrices": bool(full_matrices)})
    return out[0], out[1], out[2]


def _eigh(x, *, uplo):
    return tuple(jnp.linalg.eigh(x, UPLO=uplo))


def eigh(x, UPLO="L", name=None):
    out = dispatch.apply("eigh", _eigh, (x,), {"uplo": UPLO})
    return out[0], out[1]


def _eigvalsh(x, *, uplo):
    return jnp.linalg.eigvalsh(x, UPLO=uplo)


def eigvalsh(x, UPLO="L", name=None):
    return dispatch.apply("eigvalsh", _eigvalsh, (x,), {"uplo": UPLO})


def _eig(x):
    return tuple(jnp.linalg.eig(x))


def eig(x, name=None):
    # CPU-only in XLA; fine for the eager/debug path
    out = dispatch.apply("eig", _eig, (x,), cache=False)
    return out[0], out[1]


def _matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return dispatch.apply("matrix_power", _matrix_power, (x,), {"n": int(n)})


def _matrix_rank(x, *, tol):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return dispatch.apply("matrix_rank", _matrix_rank, (x,), {"tol": tol})


def _lstsq(a, b, *, rcond):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res, rank, sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    out = dispatch.apply("lstsq", _lstsq, (x, y), {"rcond": rcond})
    return tuple(out)


def _cond(x, *, p):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return dispatch.apply("cond", _cond, (x,), {"p": p})


def _lu(x):
    import jax.scipy.linalg as jsl

    lu_mat, piv = jsl.lu_factor(x)
    # paddle's lu contract is 1-based LAPACK pivots; jax returns 0-based
    return lu_mat, piv.astype(jnp.int32) + 1


def lu(x, pivot=True, get_infos=False, name=None):
    out = dispatch.apply("lu", _lu, (x,))
    if get_infos:
        from .creation import zeros

        return out[0], out[1], zeros([1], dtype="int32")
    return out[0], out[1]


def _einsum(*operands, equation):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return dispatch.apply("einsum", _einsum, tuple(operands), {"equation": equation})


def _multi_dot(*mats):
    return jnp.linalg.multi_dot(mats)


def multi_dot(x, name=None):
    return dispatch.apply("multi_dot", _multi_dot, tuple(x))


def _reflector(a, i):
    """i-th geqrf Householder vector: unit at i, a[i+1:, i] below."""
    m = a.shape[-2]
    v = jnp.where(jnp.arange(m) > i, a[..., :, i], 0.0)
    return v.at[..., i].set(1.0)


def _householder_product(a, tau):
    # form Q from householder reflectors (geqrf layout): H = I - tau v v^H
    m, n = a.shape[-2], a.shape[-1]
    q = jnp.eye(m, dtype=a.dtype)
    q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q

    def body(i, q):
        v = _reflector(a, i)
        t = tau[..., i]
        vvt = jnp.einsum("...i,...j->...ij", v, jnp.conj(v))
        h = jnp.eye(m, dtype=a.dtype) - t[..., None, None] * vvt
        return jnp.matmul(q, h)

    q = jax.lax.fori_loop(0, n, body, q)
    return q[..., :, :n]


def householder_product(x, tau, name=None):
    return dispatch.apply("householder_product", _householder_product, (x, tau))


def _eigvals(a):
    return jnp.linalg.eigvals(a)


def eigvals(x, name=None):
    return dispatch.apply("eigvals", _eigvals, (x,), nondiff=True)


def _svdvals(a):
    return jnp.linalg.svdvals(a)


def svdvals(x, name=None):
    return dispatch.apply("svdvals", _svdvals, (x,))


def _matrix_exp(a):
    return jax.scipy.linalg.expm(a)


def matrix_exp(x, name=None):
    return dispatch.apply("matrix_exp", _matrix_exp, (x,))


def _lu_perm(piv, m):
    """LAPACK sequential-swap pivots -> permutation vector over rows."""
    perm = jnp.arange(m, dtype=jnp.int32)

    def body(i, perm):
        j = piv[i]
        pi, pj = perm[i], perm[j]
        return perm.at[i].set(pj).at[j].set(pi)

    return jax.lax.fori_loop(0, piv.shape[0], body, perm)


def _lu_unpack(lu_mat, piv, *, unpack_ludata, unpack_pivots):
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    outs = []
    if unpack_pivots:
        perm_fn = _lu_perm
        for _ in range(piv.ndim - 1):  # batched pivots
            perm_fn = jax.vmap(perm_fn, in_axes=(0, None))
        perm = perm_fn(piv - 1, m)  # pivots are 1-based (LAPACK contract)
        # rows perm of A equal L@U, so A = P @ L @ U with P[perm[i], i]=1
        p = jnp.swapaxes(
            jnp.take(jnp.eye(m, dtype=lu_mat.dtype), perm, axis=0), -2, -1
        )
        outs.append(p)
    else:
        outs.append(jnp.zeros((0,), lu_mat.dtype))
    if unpack_ludata:
        lower = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(
            m, k, dtype=lu_mat.dtype
        )
        upper = jnp.triu(lu_mat[..., :k, :])
        outs.extend([lower, upper])
    else:
        z = jnp.zeros((0,), lu_mat.dtype)
        outs.extend([z, z])
    return tuple(outs)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack ``paddle.linalg.lu`` output into (P, L, U) with A = P @ L @ U."""
    return dispatch.apply(
        "lu_unpack", _lu_unpack, (x, y),
        {"unpack_ludata": bool(unpack_ludata),
         "unpack_pivots": bool(unpack_pivots)},
    )


def _ormqr(a, tau, other, *, left, transpose):
    # Apply the k reflectors H_i = I - tau_i v_i v_i^H directly to `other`
    # (O(k*m*p)) instead of materialising the full m x m Q. Q = H_0...H_{k-1};
    # Q^H applies conjugated taus in the opposite order.
    k = tau.shape[-1]

    def step(i, x):
        idx = k - 1 - i if (left != transpose) else i
        v = _reflector(a, idx)
        t = jnp.conj(tau[..., idx]) if transpose else tau[..., idx]
        if left:
            # x <- x - t * v (v^H x)
            vx = jnp.einsum("...m,...mp->...p", jnp.conj(v), x)
            return x - t[..., None, None] * jnp.einsum(
                "...m,...p->...mp", v, vx
            )
        # x <- x - t * (x v) v^H
        xv = jnp.einsum("...pm,...m->...p", x, v)
        return x - t[..., None, None] * jnp.einsum(
            "...p,...m->...pm", xv, jnp.conj(v)
        )

    return jax.lax.fori_loop(0, k, step, other)


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply ``other`` by the Q of a geqrf-style (x, tau) factorization."""
    return dispatch.apply(
        "ormqr", _ormqr, (x, tau, other),
        {"left": bool(left), "transpose": bool(transpose)},
    )


def _svd_lowrank(a, g, *, q, niter):
    # randomized range finder (Halko et al.): Y = A G; power iterations
    # refine the subspace; then svd of the small projected matrix.
    # batched: transposes swap only the trailing matrix axes
    def ht(m):
        return jnp.swapaxes(m, -2, -1).conj()

    y = a @ g
    qmat, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        z = ht(a) @ qmat
        qmat, _ = jnp.linalg.qr(z)
        y = a @ qmat
        qmat, _ = jnp.linalg.qr(y)
    b = ht(qmat) @ a
    u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u_b, s, ht(vh)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD: returns (U, S, V) with ~q components
    (reference: linalg.svd_lowrank; Halko-Martinsson-Tropp sketch)."""
    from ..core import random as random_mod

    if M is not None:
        from .math import subtract

        x = subtract(x, M)
    from ..core.tensor import Tensor as _T

    n = int(x.shape[-1])
    k = min(int(q), n)
    batch = tuple(int(d) for d in x.shape[:-2])
    g = jax.random.normal(
        random_mod.next_key(), batch + (n, k),
        dtype=x.value.dtype if hasattr(x, "value") else jnp.float32,
    )
    return dispatch.apply(
        "svd_lowrank",
        lambda a, gg: _svd_lowrank(a, gg, q=k, niter=int(niter)),
        (x, _T(g)), cache=False,
    )
