"""Elementwise & general math ops.

Reference parity: python/paddle/tensor/math.py + phi math kernels
(reference: paddle/phi/kernels/ — unverified, mount empty). Each op is one
pure jnp function; XLA fuses chains of these into single TPU kernels, which
is why there are no hand-written fused elementwise kernels here (the
reference needs CUDA fusion passes for that; XLA does it natively).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import enforce as _enf
from ._helpers import binary, normalize_axis, unary

# ---------------------------------------------------------------- elementwise


def _add(x, y):
    return jnp.add(x, y)


def _sub(x, y):
    return jnp.subtract(x, y)


def _mul(x, y):
    return jnp.multiply(x, y)


def _div(x, y):
    return jnp.true_divide(x, y)


def _floordiv(x, y):
    return jnp.floor_divide(x, y)


def _mod(x, y):
    return jnp.mod(x, y)


def _pow(x, y):
    return jnp.power(x, y)


def _maximum(x, y):
    return jnp.maximum(x, y)


def _minimum(x, y):
    return jnp.minimum(x, y)


def _fmax(x, y):
    return jnp.fmax(x, y)


def _fmin(x, y):
    return jnp.fmin(x, y)


def _atan2(x, y):
    return jnp.arctan2(x, y)


def _hypot(x, y):
    return jnp.hypot(x, y)


def _remainder(x, y):
    return jnp.remainder(x, y)


add = binary("add", _add)
subtract = binary("subtract", _sub)
multiply = binary("multiply", _mul)
divide = binary("divide", _div)
floor_divide = binary("floor_divide", _floordiv)
mod = binary("mod", _mod)
remainder = binary("remainder", _remainder)
floor_mod = mod
pow = binary("pow", _pow)
maximum = binary("maximum", _maximum)
minimum = binary("minimum", _minimum)
fmax = binary("fmax", _fmax)
fmin = binary("fmin", _fmin)
atan2 = binary("atan2", _atan2)
hypot = binary("hypot", _hypot)

sqrt = unary("sqrt", jnp.sqrt)
rsqrt = unary("rsqrt", lambda x: jax.lax.rsqrt(x))
exp = unary("exp", jnp.exp)
expm1 = unary("expm1", jnp.expm1)
log = unary("log", jnp.log)
log2 = unary("log2", jnp.log2)
log10 = unary("log10", jnp.log10)
log1p = unary("log1p", jnp.log1p)
abs = unary("abs", jnp.abs)
neg = unary("neg", jnp.negative)
sign = unary("sign", jnp.sign)
sin = unary("sin", jnp.sin)
cos = unary("cos", jnp.cos)
tan = unary("tan", jnp.tan)
asin = unary("asin", jnp.arcsin)
acos = unary("acos", jnp.arccos)
atan = unary("atan", jnp.arctan)
sinh = unary("sinh", jnp.sinh)
cosh = unary("cosh", jnp.cosh)
tanh = unary("tanh", jnp.tanh)
asinh = unary("asinh", jnp.arcsinh)
acosh = unary("acosh", jnp.arccosh)
atanh = unary("atanh", jnp.arctanh)
erf = unary("erf", jax.scipy.special.erf)
erfinv = unary("erfinv", jax.scipy.special.erfinv)
floor = unary("floor", jnp.floor)
ceil = unary("ceil", jnp.ceil)
round = unary("round", jnp.round)
trunc = unary("trunc", jnp.trunc)
frac = unary("frac", lambda x: x - jnp.trunc(x))
reciprocal = unary("reciprocal", jnp.reciprocal)
square = unary("square", jnp.square)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
logit = unary("logit", jax.scipy.special.logit)
digamma = unary("digamma", jax.scipy.special.digamma)
lgamma = unary("lgamma", jax.scipy.special.gammaln)
i0 = unary("i0", lambda x: jax.scipy.special.i0(x))
i0e = unary("i0e", lambda x: jax.scipy.special.i0e(x))
i1e = unary("i1e", lambda x: jax.scipy.special.i1e(x))
angle = unary("angle", jnp.angle)
conj = unary("conj", jnp.conj)
real = unary("real", jnp.real)
imag = unary("imag", jnp.imag)

isfinite = unary("isfinite", jnp.isfinite, nondiff=True)
isinf = unary("isinf", jnp.isinf, nondiff=True)
isnan = unary("isnan", jnp.isnan, nondiff=True)


def _scale(x, *, scale_v, bias, bias_after_scale):
    if bias_after_scale:
        return x * scale_v + bias
    return (x + bias) * scale_v


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = dispatch.apply(
        "scale",
        _scale,
        (x,),
        {
            "scale_v": float(scale),
            "bias": float(bias),
            "bias_after_scale": bool(bias_after_scale),
        },
    )
    return out


def _clip(x, mn, mx):
    return jnp.clip(x, mn, mx)


def clip(x, min=None, max=None, name=None):
    return dispatch.apply("clip", _clip, (x, min, max))


def _lerp(x, y, w):
    return x + w * (y - x)


def lerp(x, y, weight, name=None):
    return dispatch.apply("lerp", _lerp, (x, y, weight))


def _nan_to_num(x, *, nan, posinf, neginf):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return dispatch.apply(
        "nan_to_num",
        _nan_to_num,
        (x,),
        {"nan": nan, "posinf": posinf, "neginf": neginf},
    )


def _stanh(x, *, scale_a, scale_b):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return dispatch.apply(
        "stanh", _stanh, (x,), {"scale_a": scale_a, "scale_b": scale_b}
    )


def _rsqrt_eps(x, *, eps):
    return jax.lax.rsqrt(x + eps)


# -------------------------------------------------------------------- matmul


def _matmul(x, y, *, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    _enf.check_ndim("matmul", "x", x, min_ndim=1)
    _enf.check_ndim("matmul", "y", y, min_ndim=1)
    if len(getattr(x, "shape", ())) > 1 and len(
        getattr(y, "shape", ())
    ) > 1:
        _enf.check_same_trailing(
            "matmul", "x", x, "y", y,
            dim_x=-2 if transpose_x else -1,
            dim_y=-1 if transpose_y else -2,
        )
    elif not transpose_x and not transpose_y:
        _enf.check_same_trailing("matmul", "x", x, "y", y)
    return dispatch.apply(
        "matmul",
        _matmul,
        (x, y),
        {"transpose_x": bool(transpose_x), "transpose_y": bool(transpose_y)},
    )


def _mm(x, y):
    return jnp.matmul(x, y)


mm = binary("mm", _mm)


def _bmm(x, y):
    return jnp.matmul(x, y)


bmm = binary("bmm", _bmm)


def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


dot = binary("dot", _dot)


def _inner(x, y):
    return jnp.inner(x, y)


inner = binary("inner", _inner)


def _outer(x, y):
    return jnp.outer(x, y)


outer = binary("outer", _outer)


def _addmm(inp, x, y, *, beta, alpha):
    return beta * inp + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return dispatch.apply(
        "addmm", _addmm, (input, x, y), {"beta": beta, "alpha": alpha}
    )


def _kron(x, y):
    return jnp.kron(x, y)


kron = binary("kron", _kron)


def _cross(x, y, *, axis):
    return jnp.cross(x, y, axis=axis if axis is not None else -1)


def cross(x, y, axis=9, name=None):
    # paddle defaults to the first axis with dim 3; approximate with given axis
    if axis == 9:
        ax = None
        for i, d in enumerate(x.shape):
            if d == 3:
                ax = i
                break
        axis = ax if ax is not None else -1
    return dispatch.apply("cross", _cross, (x, y), {"axis": int(axis)})


# ------------------------------------------------------------------ cumulative


def _cumsum(x, *, axis):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    out = dispatch.apply("cumsum", _cumsum, (x,), {"axis": normalize_axis(axis)})
    if dtype is not None:
        from .manipulation import cast

        out = cast(out, dtype)
    return out


def _cumprod(x, *, axis):
    return jnp.cumprod(x, axis=axis)


def cumprod(x, dim=None, dtype=None, name=None):
    out = dispatch.apply("cumprod", _cumprod, (x,), {"axis": normalize_axis(dim)})
    if dtype is not None:
        from .manipulation import cast

        out = cast(out, dtype)
    return out


def _logcumsumexp(x, *, axis):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    out = dispatch.apply(
        "logcumsumexp", _logcumsumexp, (x,), {"axis": normalize_axis(axis)}
    )
    if dtype is not None:
        from .manipulation import cast

        out = cast(out, dtype)
    return out


def _logaddexp(x, y):
    return jnp.logaddexp(x, y)


logaddexp = binary("logaddexp", _logaddexp)


def _trace(x, *, offset, axis1, axis2):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch.apply(
        "trace",
        _trace,
        (x,),
        {"offset": int(offset), "axis1": int(axis1), "axis2": int(axis2)},
    )


def _diff(x, *, n, axis):
    return jnp.diff(x, n=n, axis=axis)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    if prepend is not None or append is not None:
        from .manipulation import concat

        parts = []
        if prepend is not None:
            parts.append(prepend)
        parts.append(x)
        if append is not None:
            parts.append(append)
        x = concat(parts, axis=axis)
    return dispatch.apply("diff", _diff, (x,), {"n": int(n), "axis": int(axis)})


def _multiply_no_grad_accum(x, y):  # helper used by optimizers
    return x * y
