"""Comparison & logical ops.

Reference parity: python/paddle/tensor/logic.py (unverified, mount empty).
Comparisons return bool tensors and are non-differentiable (stop_gradient
outputs), matching the reference.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ._helpers import binary


def _cmp(name, jfn):
    def op(x, y, name=None):
        # comparisons are non-differentiable: bool outputs, no GradNode
        return dispatch.apply(op_name, fn, (x, y), nondiff=True)

    def fn(xv, yv):
        return jfn(xv, yv)

    fn.__name__ = "_" + name
    op_name = name
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)


def _to_bool(v):
    return v.astype(bool) if hasattr(v, "astype") else bool(v)


def _and(x, y):
    return jnp.logical_and(x, y)


def _or(x, y):
    return jnp.logical_or(x, y)


def _xor(x, y):
    return jnp.logical_xor(x, y)


logical_and = binary("logical_and", _and, nondiff=True)
logical_or = binary("logical_or", _or, nondiff=True)
logical_xor = binary("logical_xor", _xor, nondiff=True)


def logical_not(x, out=None, name=None):
    return dispatch.apply("logical_not", jnp.logical_not, (x,), nondiff=True)


def _band(x, y):
    return jnp.bitwise_and(x, y)


def _bor(x, y):
    return jnp.bitwise_or(x, y)


def _bxor(x, y):
    return jnp.bitwise_xor(x, y)


bitwise_and = binary("bitwise_and", _band, nondiff=True)
bitwise_or = binary("bitwise_or", _bor, nondiff=True)
bitwise_xor = binary("bitwise_xor", _bxor, nondiff=True)


def bitwise_not(x, out=None, name=None):
    return dispatch.apply("bitwise_not", jnp.bitwise_not, (x,), nondiff=True)


def _isclose(x, y, *, rtol, atol, equal_nan):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return dispatch.apply(
        "isclose",
        _isclose,
        (x, y),
        {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)},
        nondiff=True,
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    def _allclose(xv, yv, *, rtol, atol, equal_nan):
        return jnp.allclose(xv, yv, rtol=rtol, atol=atol, equal_nan=equal_nan)

    return dispatch.apply(
        "allclose",
        _allclose,
        (x, y),
        {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)},
        cache=False,
        nondiff=True,
    )


def equal_all(x, y, name=None):
    def _equal_all(xv, yv):
        if xv.shape != yv.shape:
            return jnp.asarray(False)
        return jnp.all(xv == yv)

    return dispatch.apply("equal_all", _equal_all, (x, y), cache=False, nondiff=True)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def _shift_left(x, y):
    return jnp.left_shift(x, y)


def _shift_right(x, y):
    return jnp.right_shift(x, y)


bitwise_left_shift = binary("bitwise_left_shift", _shift_left)
bitwise_right_shift = binary("bitwise_right_shift", _shift_right)
