"""Inplace-suffix op variants (reference: the ``op_``/``Tensor.op_``
family generated from paddle's inplace op registry — unverified).

jax arrays are immutable, so "inplace" here is the framework's
value-swap contract: ``x._inplace(op, ...)`` computes out-of-place,
snapshots x's autograd identity as the op's input, and rebinds x to the
result — user-visible semantics (including grad history) match the
reference's inplace ops without aliasing mutation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import random as random_mod
from ..core.tensor import Tensor
from . import extras, manipulation, math, search, tail


def _mk(name, fn):
    def op(x, *args, **kw):
        return x._inplace(fn, *args, **kw)

    op.__name__ = name
    op.__qualname__ = name
    return op


# ----------------------------------------------------------------- unary
exp_ = _mk("exp_", math.exp)
sqrt_ = _mk("sqrt_", math.sqrt)
rsqrt_ = _mk("rsqrt_", math.rsqrt)
ceil_ = _mk("ceil_", math.ceil)
floor_ = _mk("floor_", math.floor)
round_ = _mk("round_", math.round)
reciprocal_ = _mk("reciprocal_", math.reciprocal)
tanh_ = _mk("tanh_", math.tanh)
sigmoid_ = _mk("sigmoid_", math.sigmoid)
clip_ = _mk("clip_", math.clip)
scale_ = _mk("scale_", math.scale)
tril_ = _mk("tril_", manipulation.tril)
triu_ = _mk("triu_", manipulation.triu)
cumsum_ = _mk("cumsum_", math.cumsum)
flatten_ = _mk("flatten_", manipulation.flatten)
t_ = _mk("t_", manipulation.t)

# ---------------------------------------------------------------- binary
add_ = _mk("add_", math.add)
subtract_ = _mk("subtract_", math.subtract)
multiply_ = _mk("multiply_", math.multiply)
remainder_ = _mk("remainder_", math.remainder)
copysign_ = _mk("copysign_", tail.copysign)
lerp_ = _mk("lerp_", math.lerp)
masked_fill_ = _mk("masked_fill_", manipulation.masked_fill)
renorm_ = _mk("renorm_", extras.renorm)
index_add_ = _mk("index_add_", extras.index_add)
index_put_ = _mk("index_put_", search.index_put)
put_along_axis_ = _mk("put_along_axis_", manipulation.put_along_axis)
scatter_ = _mk("scatter_", manipulation.scatter)


def relu_(x, name=None):
    from ..nn.functional.activation import relu

    return x._inplace(relu)


def softmax_(x, axis=-1, name=None):
    from ..nn.functional.activation import softmax

    return x._inplace(softmax, axis)


def where_(condition, x, y, name=None):
    """Inplace into ``x`` (reference Tensor.where_ contract)."""
    return x._inplace(
        lambda alias: manipulation.where(condition, alias, y)
    )


# -------------------------------------------------------------- fillers
def _full_like_val(x, *, v):
    return jnp.full_like(x, v)


def fill_(x, value, name=None):
    return x._inplace(
        lambda alias: dispatch.apply(
            "fill_like", _full_like_val, (alias,), {"v": float(value)}
        )
    )


def zero_(x, name=None):
    return fill_(x, 0.0)


def _fill_diagonal(xv, *, v, offset, wrap):
    nd = xv.ndim
    if nd == 2:
        m, n = xv.shape
        if wrap and m > n:
            # numpy wrap semantics: flat stride n+1 through the whole
            # array (one skipped row between wrapped diagonal blocks)
            idx = jnp.arange(0, m * n, n + 1)
            return xv.reshape(-1).at[idx].set(v).reshape(m, n)
        length = min(m, n - offset) if offset >= 0 else min(m + offset, n)
        length = max(length, 0)
        r = jnp.arange(length)
        rows = r if offset >= 0 else r - offset
        cols = r + offset if offset >= 0 else r
        return xv.at[rows, cols].set(v)
    # ndim > 2: reference fills the main hyper-diagonal x[i, i, ..., i]
    k = min(xv.shape)
    r = jnp.arange(k)
    return xv.at[tuple([r] * nd)].set(v)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    return x._inplace(
        lambda alias: dispatch.apply(
            "fill_diagonal", _fill_diagonal, (alias,),
            {"v": float(value), "offset": int(offset), "wrap": bool(wrap)},
        )
    )


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    from .tail import diagonal_scatter

    return diagonal_scatter(x, y, offset=offset, axis1=dim1, axis2=dim2)


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    from .tail import diagonal_scatter

    return x._inplace(
        lambda alias: diagonal_scatter(
            alias, y, offset=offset, axis1=dim1, axis2=dim2
        )
    )


# -------------------------------------------------------- random fillers
def _rand_fill(name, sampler, kworder):
    """kworder maps the reference keyword names onto positional slots so
    keyword calls (x.uniform_(min=0, max=2)) behave identically."""

    def op(x, *args, **kw):
        kw.pop("name", None)
        args = list(args)
        for i, key in enumerate(kworder):
            if key in kw:
                if i < len(args):
                    raise TypeError(
                        f"{name}: got multiple values for argument {key!r}"
                    )
                while len(args) < i:
                    args.append(_RAND_DEFAULTS[name][len(args)])
                args.append(kw.pop(key))
        if kw:
            raise TypeError(f"{name}: unexpected arguments {sorted(kw)}")

        def fill(alias):
            return dispatch.apply(
                name, sampler, (alias,),
                {"key": random_mod.next_key(),
                 "args": tuple(float(a) for a in args)},
                cache=False, nondiff=True,
            )

        return x._inplace(fill)

    op.__name__ = name
    return op


def _defaults(args, defaults):
    """Positional args fill left-to-right; missing slots take defaults."""
    return args + defaults[len(args):]


def _normal_sampler(x, *, key, args):
    mean, std = _defaults(args, (0.0, 1.0))
    return mean + std * jax.random.normal(key, x.shape, x.dtype)


def _uniform_sampler(x, *, key, args):
    lo, hi = _defaults(args, (-1.0, 1.0))
    return jax.random.uniform(key, x.shape, x.dtype, minval=lo, maxval=hi)


def _exponential_sampler(x, *, key, args):
    (lam,) = _defaults(args, (1.0,))
    return jax.random.exponential(key, x.shape, x.dtype) / lam


def _geometric_sampler(x, *, key, args):
    (p,) = _defaults(args, (0.5,))
    u = jax.random.uniform(key, x.shape, x.dtype)
    return jnp.floor(jnp.log1p(-u) / jnp.log1p(-p)) + 1.0


def _cauchy_sampler(x, *, key, args):
    loc, scale = _defaults(args, (0.0, 1.0))
    return loc + scale * jax.random.cauchy(key, x.shape, x.dtype)


def _log_normal_sampler(x, *, key, args):
    mean, std = _defaults(args, (1.0, 2.0))
    return jnp.exp(mean + std * jax.random.normal(key, x.shape, x.dtype))


_RAND_DEFAULTS = {
    "normal_": (0.0, 1.0),
    "uniform_": (-1.0, 1.0),
    "exponential_": (1.0,),
    "geometric_": (0.5,),
    "cauchy_": (0.0, 1.0),
    "log_normal_": (1.0, 2.0),
}

normal_ = _rand_fill("normal_", _normal_sampler, ("mean", "std"))
uniform_ = _rand_fill("uniform_", _uniform_sampler, ("min", "max"))
exponential_ = _rand_fill("exponential_", _exponential_sampler, ("lam",))
geometric_ = _rand_fill("geometric_", _geometric_sampler, ("probs",))
cauchy_ = _rand_fill("cauchy_", _cauchy_sampler, ("loc", "scale"))
log_normal_ = _rand_fill("log_normal_", _log_normal_sampler, ("mean", "std"))
