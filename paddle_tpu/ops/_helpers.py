"""Op-definition helpers: thin factories over core.dispatch.apply."""
from __future__ import annotations

from ..core import dispatch
from ..core.tensor import Tensor


def unary(name, fn, nondiff=False):
    def op(x, name=None):
        return dispatch.apply(op_name, fn, (x,), nondiff=op_nondiff)

    op_name = name
    op_nondiff = nondiff
    op.__name__ = name
    op.__qualname__ = name
    return op


def binary(name, fn, nondiff=False):
    def op(x, y, name=None):
        return dispatch.apply(op_name, fn, (x, y), nondiff=op_nondiff)

    op_name = name
    op_nondiff = nondiff
    op.__name__ = name
    op.__qualname__ = name
    return op


def normalize_axis(axis):
    """Make axis hashable/static (lists -> tuples)."""
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, list):
        return tuple(int(a) for a in axis)
    if isinstance(axis, tuple):
        return tuple(int(a) for a in axis)
    if axis is None:
        return None
    return int(axis)


def static_int_list(v):
    if isinstance(v, Tensor):
        v = v.tolist()
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return int(v)
