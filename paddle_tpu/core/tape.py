"""Eager autograd graph state.

Reference parity: the eager autograd engine — GradNodeBase/AutogradMeta and
egr::RunBackward (reference: paddle/fluid/eager/backward.cc, grad_node_info.h
— unverified, mount empty). TPU-first redesign: instead of per-op hand-written
grad nodes, every eager op call records a ``GradNode`` holding the jax VJP
closure produced by ``jax.vjp`` at call time. The backward walk is a plain
reverse-topological traversal over these nodes. The *performance* path is a
whole-step ``jax.jit`` (see paddle_tpu/jit) where XLA differentiates the full
program; this tape is the imperative/debug path, exactly the split SURVEY.md
§7 prescribes.
"""
from __future__ import annotations

import contextlib
import threading


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents."""

    __slots__ = (
        "name",
        "vjp_fn",
        "inputs",
        "out_meta",
        "n_outputs",
        "out_refs",
        "multi",
        "__weakref__",
    )

    def __init__(self, name, vjp_fn, inputs, out_meta, multi=False):
        self.name = name
        self.vjp_fn = vjp_fn  # callable: out_cts -> tuple(in_cts)
        self.inputs = inputs  # list[Tensor] — differentiable inputs only
        self.out_meta = out_meta  # list[(shape, dtype)] per output
        self.n_outputs = len(out_meta)
        self.out_refs = [None] * len(out_meta)  # weakrefs to output Tensors
        self.multi = multi  # whether vjp_fn takes a tuple of cotangents

    def release(self):
        # Drop residuals so memory frees as backward consumes the graph
        self.vjp_fn = None
        self.inputs = ()

    def __repr__(self):
        return f"GradNode<{self.name}>"


class _AutogradState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        # tracing depth > 0 means we are inside a functional jax trace
        # (to_static / jitted train step); per-op jit must be skipped so the
        # outer jit sees raw jax ops and can fuse them.
        self.trace_depth = 0


STATE = _AutogradState()


def grad_enabled() -> bool:
    return STATE.grad_enabled


def is_grad_enabled() -> bool:
    return STATE.grad_enabled


def in_trace() -> bool:
    return STATE.trace_depth > 0


@contextlib.contextmanager
def trace_scope():
    """Mark that ops should execute as raw jax calls (inside an outer jit)."""
    STATE.trace_depth += 1
    try:
        yield
    finally:
        STATE.trace_depth -= 1


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad parity: usable as context manager and decorator."""

    def __enter__(self):
        self._prev = STATE.grad_enabled
        STATE.grad_enabled = False
        return self

    def __exit__(self, *exc):
        STATE.grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = STATE.grad_enabled
        STATE.grad_enabled = True
        return self

    def __exit__(self, *exc):
        STATE.grad_enabled = self._prev
        return False


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = STATE.grad_enabled
    STATE.grad_enabled = bool(mode)
    try:
        yield
    finally:
        STATE.grad_enabled = prev
