"""Device/Place layer.

Reference parity: phi::Place + DeviceContextPool + paddle.set_device
(reference: paddle/phi/common/place.h, paddle/phi/core/device_context.cc —
unverified, mount empty). On TPU there is no per-stream context to manage: XLA
owns scheduling. This layer is therefore a thin selection mechanism that
routes creation ops (and jit compilation) onto a chosen jax.Device, plus the
CustomDevice-style "fake backend" trick for CI: ``set_device('cpu')`` runs the
whole framework on host CPU (the analog of the reference's custom_cpu plugin
test backend, test/custom_runtime/ — unverified).
"""
from __future__ import annotations

import threading

import jax


class Place:
    """Device identity, paddle.CPUPlace()/TPUPlace(id) analog."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id: int = 0):
    return Place("tpu", device_id)


class _DeviceState(threading.local):
    def __init__(self):
        self.place = None  # lazily resolved


_STATE = _DeviceState()

# Platforms we treat as "the accelerator" in preference order. "axon" is how
# a tunneled TPU chip shows up; "tpu" is the native platform name.
_TPU_PLATFORMS = ("tpu", "axon")


def _accelerator_devices():
    devs = jax.devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel


def _default_place() -> Place:
    if _accelerator_devices():
        return Place("tpu", 0)
    return Place("cpu", 0)


def set_device(device) -> Place:
    """paddle.set_device parity. Accepts 'cpu', 'tpu', 'tpu:1', Place."""
    if isinstance(device, Place):
        _STATE.place = device
        return device
    if not isinstance(device, str):
        raise TypeError(f"set_device expects str or Place, got {type(device)}")
    dev = device.lower()
    # The reference's gpu place maps to the accelerator here so that
    # reference scripts run unmodified ("gpu" -> the TPU chip).
    if dev.startswith("gpu"):
        dev = "tpu" + dev[3:]
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        place = Place(kind, int(idx))
    else:
        place = Place(dev, 0)
    if place.device_type not in ("cpu", "tpu"):
        raise ValueError(f"unknown device {device!r}; expected cpu/tpu[:i]")
    _STATE.place = place
    # Steer jax's default device so eager computation stays on the chosen
    # backend (otherwise ops on freshly created arrays bounce to whatever
    # backend is jax's global default — catastrophic over a tunneled chip).
    try:
        jax.config.update("jax_default_device", jax_device(place))
    except Exception:
        pass  # backend not initializable yet (e.g. restricted CI) — harmless
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    if _STATE.place is None:
        _STATE.place = _default_place()
    return _STATE.place


def jax_device(place: Place | None = None):
    """Resolve a Place to a concrete jax.Device (local)."""
    p = place or current_place()
    if p.device_type == "cpu":
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        if not cpus:
            # jax can always materialize host CPU devices
            cpus = jax.devices("cpu")
        return cpus[min(p.device_id, len(cpus) - 1)]
    accel = _accelerator_devices()
    if not accel:
        # fake-backend mode: 'tpu' place on a CPU-only host (CI) routes to CPU,
        # mirroring the reference's custom_cpu plugin trick.
        return jax_device(Place("cpu", p.device_id))
    return accel[min(p.device_id, len(accel) - 1)]


def is_compiled_with_cuda() -> bool:  # reference API parity
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    """Local visible device count for the current place kind."""
    p = current_place()
    if p.device_type == "cpu":
        return len([d for d in jax.devices() if d.platform == "cpu"]) or 1
    return len(_accelerator_devices()) or 1


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role by design (SURVEY §7); the flag answers the
    # reference question "is a tensor compiler available" truthfully
    return True


def is_compiled_with_distribute() -> bool:
    return True


def CUDAPlace(device_id: int = 0):
    """Reference scripts constructing CUDAPlace run on the accelerator
    this build targets (TPU) — same role, same API shape."""
    return TPUPlace(device_id)


def XPUPlace(device_id: int = 0):
    return TPUPlace(device_id)


def CUDAPinnedPlace():
    return Place("cpu", 0)


def CustomPlace(device_type: str, device_id: int = 0):
    return Place(str(device_type), int(device_id))
