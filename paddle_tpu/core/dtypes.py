"""Dtype registry for paddle_tpu.

Reference parity: paddle exposes dtype objects (``paddle.float32`` etc.) used
across the tensor API (reference: paddle/phi/common/data_type.h — unverified,
mount empty; see SURVEY.md caveat). On TPU we map every public dtype directly
onto the JAX/NumPy dtype system so arrays never need conversion at dispatch
time; bfloat16 is first-class (it is the MXU-native matmul dtype).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects. These ARE numpy dtype-compatible objects, so
# ``jnp.zeros(shape, dtype=paddle_tpu.float32)`` works with no translation.
bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_TO_DTYPE = {
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}

#: default dtype for floating-point tensor creation (paddle default: float32)
_default_dtype = [np.dtype("float32")]


def set_default_dtype(d):
    """paddle.set_default_dtype parity."""
    _default_dtype[0] = np.dtype(convert_dtype(d))


def get_default_dtype():
    return _default_dtype[0]


def convert_dtype(dtype):
    """Normalize any user-facing dtype spec to a numpy dtype.

    Accepts strings ("float32", "bf16"), numpy dtypes, jnp dtypes, python
    types (float/int/bool), and paddle-style "paddle.float32" reprs.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.split(".")[-1].lower()
        if key in _STR_TO_DTYPE:
            return np.dtype(_STR_TO_DTYPE[key])
        return np.dtype(dtype)
    if dtype is float:
        return np.dtype(_default_dtype[0])
    if dtype is int:
        return np.dtype("int64")
    if dtype is bool:
        return np.dtype("bool")
    return np.dtype(dtype)


def is_floating_point_dtype(dtype) -> bool:
    d = np.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.floating)


def is_complex_dtype(dtype) -> bool:
    d = np.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.complexfloating)


def is_differentiable_dtype(dtype) -> bool:
    return is_floating_point_dtype(dtype) or is_complex_dtype(dtype)


def is_integer_dtype(dtype) -> bool:
    d = np.dtype(convert_dtype(dtype))
    return jnp.issubdtype(d, jnp.integer)


class _FInfo:
    """paddle.finfo parity (float type limits)."""

    def __init__(self, dtype):
        # jnp.finfo handles bfloat16/float8 via ml_dtypes, numpy the rest
        import jax.numpy as jnp

        info = jnp.finfo(convert_dtype(dtype))
        self.dtype = str(info.dtype)
        self.bits = info.bits
        self.eps = float(info.eps)
        self.min = float(info.min)
        self.max = float(info.max)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)


class _IInfo:
    """paddle.iinfo parity (integer type limits)."""

    def __init__(self, dtype):
        info = np.iinfo(np.dtype(convert_dtype(dtype)))
        self.dtype = str(info.dtype)
        self.bits = info.bits
        self.min = int(info.min)
        self.max = int(info.max)


def finfo(dtype):
    return _FInfo(dtype)


def iinfo(dtype):
    return _IInfo(dtype)
