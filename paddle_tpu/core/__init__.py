"""Core runtime: tensor, dtype, device, dispatch, autograd state, RNG."""
from . import device, dispatch, dtypes, random, tape, tensor  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace,
    Place,
    TPUPlace,
    get_device,
    set_device,
)
from .dtypes import convert_dtype, get_default_dtype, set_default_dtype  # noqa: F401
from .tensor import Parameter, Tensor, is_tensor  # noqa: F401
