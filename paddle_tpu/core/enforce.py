"""Enforce-style argument validation (paddle error-message parity).

Reference parity: paddle/common/enforce.h PADDLE_ENFORCE_* macros + the
check_variable_and_dtype/check_type helpers in python/paddle/base/
data_feeder.py (unverified, mount empty). The reference wraps every
kernel in systematic precondition checks that name the op, the argument,
the expectation, and what was actually received; without them misuse
surfaces as raw backend errors deep in the stack.

Here the highest-traffic Python entry points call these helpers so the
common mistakes fail at the API boundary with the same message shape:

    (InvalidArgument) matmul: input 'y' expected ndim >= 1, but
    received ndim 0 (shape ()).

Everything that passes the boundary checks still gets XLA's own shape
verification as the backstop — these checks exist for message quality,
not correctness.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "enforce", "check_ndim", "check_same_trailing", "check_dtype",
    "check_int_dtype", "check_type", "EnforceError",
]


class EnforceError(ValueError):
    """paddle-style precondition failure (a ValueError subclass so
    existing `except ValueError` handlers keep working)."""


def enforce(cond, op, msg, *args):
    """PADDLE_ENFORCE analog: raise (InvalidArgument) <op>: <msg> when
    ``cond`` is falsy. ``msg`` may be a format string over ``args``."""
    if not cond:
        raise EnforceError(
            f"(InvalidArgument) {op}: " + (msg.format(*args) if args
                                           else msg)
        )


def _shape_of(t):
    s = getattr(t, "shape", None)
    return tuple(s) if s is not None else None


def check_type(op, name, value, types):
    if not isinstance(value, types):
        tn = (
            "/".join(t.__name__ for t in types)
            if isinstance(types, tuple) else types.__name__
        )
        raise EnforceError(
            f"(InvalidArgument) {op}: argument '{name}' expected "
            f"{tn}, but received {type(value).__name__}"
        )


def check_ndim(op, name, t, min_ndim=None, exact_ndim=None):
    shape = _shape_of(t)
    if shape is None:
        return
    nd = len(shape)
    if exact_ndim is not None:
        allowed = (
            (exact_ndim,) if isinstance(exact_ndim, int) else tuple(exact_ndim)
        )
        enforce(
            nd in allowed, op,
            "input '{}' expected ndim {}, but received ndim {} "
            "(shape {})",
            name, " or ".join(map(str, allowed)), nd, shape,
        )
    if min_ndim is not None:
        enforce(
            nd >= min_ndim, op,
            "input '{}' expected ndim >= {}, but received ndim {} "
            "(shape {})",
            name, min_ndim, nd, shape,
        )


def check_same_trailing(op, name_x, x, name_y, y, dim_x=-1, dim_y=-2):
    """The matmul-style contract: x.shape[dim_x] == y.shape[dim_y]."""
    sx, sy = _shape_of(x), _shape_of(y)
    if sx is None or sy is None or not sx or not sy:
        return
    if len(sy) == 1:
        dim_y = -1
    a, b = sx[dim_x], sy[dim_y]
    enforce(
        int(a) == int(b), op,
        "input '{}' shape {} is not multiplicable with '{}' shape {}: "
        "{} != {}",
        name_x, sx, name_y, sy, a, b,
    )


_FLOATING = ("float16", "bfloat16", "float32", "float64",
             "complex64", "complex128")
_INTEGRAL = ("int8", "uint8", "int16", "int32", "int64", "bool")


def _dtype_name(t):
    d = getattr(t, "dtype", None)
    if d is None:
        return None
    try:
        return np.dtype(d).name
    except TypeError:
        return str(d)


def check_dtype(op, name, t, allowed=_FLOATING):
    dn = _dtype_name(t)
    if dn is None:
        return
    enforce(
        dn in allowed, op,
        "input '{}' expected dtype in {}, but received {}",
        name, list(allowed), dn,
    )


def check_int_dtype(op, name, t):
    check_dtype(op, name, t, allowed=_INTEGRAL[:-1])
