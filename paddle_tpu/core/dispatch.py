"""Op dispatch: the eager execution + autograd recording path.

Reference parity: the generated "dygraph functions" + Phi kernel dispatch
(reference: paddle/fluid/eager/api/generated/, paddle/phi/core/kernel_factory.cc
— unverified, mount empty). TPU-first redesign: there is no kernel registry —
XLA *is* the kernel library. Each op is one pure jax function; dispatch does:

  eager, no grad   -> cached ``jax.jit`` of the op (one compiled executable
                      per (op, static-kwargs, shapes) — XLA's analog of a
                      Phi kernel selection)
  eager, grad      -> ``jax.vjp`` at call time; the VJP closure becomes the
                      GradNode (replaces Paddle's generated per-op grad nodes)
  inside trace     -> raw jax call so the *outer* whole-step jit sees the op
                      and fuses it (the CINN-replacement path, SURVEY.md §3.5)

AMP hooks in paddle_tpu.amp rewrite input dtypes here, mirroring the AMP
dtype-promotion pass in the reference's generated dygraph functions.
"""
from __future__ import annotations

import functools
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from . import dtypes as dtypes_mod
from . import tape as tape_mod
from .tensor import Tensor

_JIT_CACHE: dict = {}

# amp hook: callable (op_name, vals) -> vals, installed by paddle_tpu.amp
_AMP_HOOK = [None]

# profiler hook: callable (op_name, seconds), installed by paddle_tpu.profiler
# while a Profiler is recording — the analog of the reference's auto-wrapped
# per-op RecordEvents (paddle/fluid/platform/profiler). One list-index
# check when off.
_PROFILER_HOOK = [None]


def set_amp_hook(fn):
    _AMP_HOOK[0] = fn


# armed by observability.FlightRecorder.install(): called with the op
# name BEFORE the NaN/Inf error raises, so the crash bundle is written
# while the step records are still in memory. One list-index check when
# off (the _PROFILER_HOOK pattern).
_NANINF_HOOK = [None]


def _nan_report(op_name, ok):
    if not bool(ok):
        hook = _NANINF_HOOK[0]
        if hook is not None:
            try:
                hook(op_name)
            except Exception:
                pass  # a broken recorder must not mask the NaN error
        raise RuntimeError(
            f"FLAGS_check_nan_inf: operator [{op_name}] output contains "
            "NaN or Inf"
        )


def check_nan_inf(op_name, vals):
    """FLAGS_check_nan_inf sweep (reference:
    paddle/fluid/framework/details/nan_inf_utils_detail.* — unverified).

    Eager arrays: hard raise naming the op. Traced values: a
    jax.debug.callback carries the finiteness bit to the host, which
    raises when the compiled step executes (surfaces as an
    XlaRuntimeError wrapping this message)."""
    from ..utils import flags as flags_mod

    if not flags_mod.flag("FLAGS_check_nan_inf"):
        return
    for v in vals:
        dt = getattr(v, "dtype", None)
        if dt is None or dt == jax.dtypes.float0:
            continue
        if not jnp.issubdtype(dt, jnp.floating):
            continue
        ok = jnp.all(jnp.isfinite(v))
        if isinstance(ok, jax.core.Tracer):
            jax.debug.callback(_nan_report, op_name, ok)
        elif not bool(ok):
            _nan_report(op_name, False)


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def _jitted(fn, kw):
    key = (fn, _freeze(kw))
    j = _JIT_CACHE.get(key)
    if j is None:
        j = jax.jit(functools.partial(fn, **kw)) if kw else jax.jit(fn)
        _JIT_CACHE[key] = j
    return j


_VJP_FWD_CACHE: dict = {}


def _vjp_fwd(fn, kw, diff_idx, all_vals):
    """(out, vjp_closure) over the differentiable positions only; shared
    by the cached-jitted and direct eager grad paths."""
    def f_diff(*dvals):
        full = list(all_vals)
        for i, v in zip(diff_idx, dvals):
            full[i] = v
        return fn(*full, **kw)

    return jax.vjp(f_diff, *[all_vals[i] for i in diff_idx])


def _vjp_jitted(fn, kw, diff_idx):
    """Jitted (out, vjp_fn) forward for the eager grad path; see the
    autograd section of _apply. jax re-keys on arg shapes/arity
    internally, so the cache key only needs the trace-shaping statics."""
    key = (fn, _freeze(kw), diff_idx)
    j = _VJP_FWD_CACHE.get(key)
    if j is None:
        def fwd(*all_vals):
            return _vjp_fwd(fn, kw, diff_idx, all_vals)

        j = jax.jit(fwd)
        _VJP_FWD_CACHE[key] = j
    return j


def _unwrap(a):
    return a.value if isinstance(a, Tensor) else a


def _is_diff_tensor(a):
    return (
        isinstance(a, Tensor)
        and not a.stop_gradient
        and dtypes_mod.is_differentiable_dtype(a.dtype)
    )


def zero_cotangent(shape, dtype):
    """A zero cotangent matching jax.vjp's expectations (float0 for ints)."""
    d = np.dtype(dtype)
    if jnp.issubdtype(d, jnp.floating) or jnp.issubdtype(d, jnp.complexfloating):
        return jnp.zeros(shape, d)
    return np.zeros(shape, jax.dtypes.float0)


def apply(name, fn, args, kw=None, cache=True, nondiff=False):
    """Execute op ``fn`` over ``args`` (mix of Tensors and statics).

    ``fn`` must be a pure jax function taking the positional args (arrays in
    Tensor positions) plus static keyword args. Returns Tensor or tuple of
    Tensors mirroring fn's output structure. ``cache=False`` skips the per-op
    jit cache — required when ``fn`` is a per-call closure (indexing).
    ``nondiff=True`` declares the op non-differentiable (bool/int outputs):
    no GradNode is recorded and no vjp residuals are kept.
    """
    hook = _PROFILER_HOOK[0]
    if hook is not None:
        import time as _time

        t0 = _time.perf_counter()
        try:
            return _apply(name, fn, args, kw, cache, nondiff)
        finally:
            hook(name, _time.perf_counter() - t0)
    return _apply(name, fn, args, kw, cache, nondiff)


def _apply(name, fn, args, kw=None, cache=True, nondiff=False):
    kw = kw or {}
    vals = [_unwrap(a) for a in args]
    if _AMP_HOOK[0] is not None:
        vals = _AMP_HOOK[0](name, vals)

    grad_needed = (
        not nondiff
        and tape_mod.grad_enabled()
        and any(_is_diff_tensor(a) for a in args)
    )

    if not grad_needed:
        if tape_mod.in_trace() or not cache:
            out = fn(*vals, **kw)
        else:
            out = _jitted(fn, kw)(*vals)
        check_nan_inf(name, out if isinstance(out, (tuple, list)) else (out,))
        return _wrap_outputs(out, stop_gradient=True)

    # --- autograd path: vjp over the differentiable tensor args only
    diff_idx = tuple(i for i, a in enumerate(args) if _is_diff_tensor(a))
    diff_tensors = [args[i] for i in diff_idx]

    if cache and not tape_mod.in_trace():
        # cached jitted forward returning (out, vjp closure): jax.vjp
        # re-traces fn per call (~500 us/op measured), which dominated
        # eager training; the vjp closure is a jax Partial — a pytree —
        # so it round-trips through jit and the trace happens once per
        # (op, static-kwargs, diff-arg set, shapes)
        out, vjp_fn = _vjp_jitted(fn, kw, diff_idx)(*vals)
    else:
        out, vjp_fn = _vjp_fwd(fn, kw, diff_idx, vals)

    is_multi = isinstance(out, (tuple, list))
    outs = tuple(out) if is_multi else (out,)
    check_nan_inf(name, outs)
    out_meta = [(o.shape, o.dtype) for o in outs]

    node = tape_mod.GradNode(name, vjp_fn, diff_tensors, out_meta, multi=is_multi)
    wrapped = tuple(
        _make_out(o, node, i) for i, o in enumerate(outs)
    )
    return wrapped if is_multi else wrapped[0]


def _make_out(val, node, idx):
    t = Tensor(val, stop_gradient=False)
    t._node = node
    t._out_idx = idx
    node.out_refs[idx] = weakref.ref(t)
    return t


def _wrap_outputs(out, stop_gradient):
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


def custom_vjp_apply(name, inputs, outputs_vals, vjp_fn):
    """Record a hand-written GradNode (PyLayer / fused kernels).

    ``inputs``: the differentiable input Tensors; ``outputs_vals``: tuple of
    raw output arrays; ``vjp_fn``: tuple(out_cts) -> tuple(in_cts aligned
    with inputs).
    """
    grad_needed = tape_mod.grad_enabled() and any(
        _is_diff_tensor(a) for a in inputs
    )
    outs_t = tuple(outputs_vals)
    if not grad_needed:
        return tuple(Tensor(o, stop_gradient=True) for o in outs_t)
    diff_tensors = [a for a in inputs if _is_diff_tensor(a)]
    out_meta = [(o.shape, o.dtype) for o in outs_t]
    # custom vjp_fns always receive the full tuple of output cotangents and
    # must return cotangents aligned with the *differentiable* inputs.
    node = tape_mod.GradNode(name, vjp_fn, diff_tensors, out_meta, multi=True)
    return tuple(_make_out(o, node, i) for i, o in enumerate(outs_t))
