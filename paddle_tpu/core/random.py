"""RNG state management.

Reference parity: paddle.seed + the stateful generator machinery
(reference: paddle/phi/core/generator.cc — unverified, mount empty). JAX RNG
is explicit-key; this module bridges the stateful API onto keys:

- Eager: a global splittable key; every consumer splits it (stateful feel).
- Traced (jitted step): a ``key_scope`` installs a *traced* base key; each
  consumer folds in a Python-side counter, so every dropout call site gets a
  distinct, deterministic subkey per step without baking constants into the
  compiled program. The per-parallel-axis RNGStatesTracker (TP-parity dropout
  semantics) lives in paddle_tpu.distributed.fleet.meta_parallel.random and
  builds on key_scope.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class _RNGState(threading.local):
    """Lazy: creating a key touches the jax backend, which must not happen
    at import time (breaks device selection and CPU-only CI)."""

    def __init__(self):
        self._key = None
        self.scope = None  # (traced_key, [counter]) when inside a jitted step

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(0)
        return self._key

    @key.setter
    def key(self, k):
        self._key = k


_STATE = _RNGState()


def seed(value: int):
    """paddle.seed parity."""
    _STATE.key = jax.random.key(int(value))
    return _STATE.key


def next_key():
    """Return a fresh PRNG subkey, trace-safe."""
    if _STATE.scope is not None:
        base, counter = _STATE.scope
        sub = jax.random.fold_in(base, counter[0])
        counter[0] += 1
        return sub
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


@contextlib.contextmanager
def key_scope(base_key):
    """Route next_key() to fold-ins of ``base_key`` (used inside jit traces)."""
    prev = _STATE.scope
    _STATE.scope = (base_key, [0])
    try:
        yield
    finally:
        _STATE.scope = prev


def get_rng_state():
    return jax.random.key_data(_STATE.key)


def set_rng_state(state):
    _STATE.key = jax.random.wrap_key_data(np.asarray(state))
