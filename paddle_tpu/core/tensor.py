"""The paddle_tpu Tensor.

Reference parity: phi::DenseTensor + the Python Tensor facade
(reference: paddle/phi/core/dense_tensor.cc, python/paddle/tensor/ —
unverified, mount empty). TPU-first redesign: a Tensor is a thin mutable
handle around an immutable ``jax.Array``. "In-place" mutation (optimizer
updates, __setitem__, set_value) swaps the underlying array — the jax way —
while autograd metadata (``_node``/``_out_idx``/``grad``) gives the
imperative ``.backward()`` UX on top of jax VJPs. Storage, layout, strides,
and allocator concerns from the reference all collapse into jax.Array/XLA
(device memory is managed by the runtime's BFC allocator; there is nothing
idiomatic to reimplement there — see SURVEY.md §7 design stance).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import device as device_mod
from . import dtypes as dtypes_mod
from . import tape as tape_mod

# Populated by paddle_tpu/__init__.py after the ops namespace exists; dunder
# methods dispatch through it so Tensor math records autograd nodes.
_ops = None


def _bind_ops(ops_namespace):
    global _ops
    _ops = ops_namespace


class Tensor:
    __slots__ = (
        "value",
        "stop_gradient",
        "grad",
        "name",
        "persistable",
        "_node",
        "_out_idx",
        "_hooks",
        "_retain_grad",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient=True, name=None):
        self.value = value  # jax.Array (or tracer inside jit)
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self.persistable = False
        self._node = None  # GradNode that produced this tensor
        self._out_idx = 0
        self._hooks = None
        self._retain_grad = False

    # ---------------------------------------------------------------- meta
    @property
    def shape(self):
        return list(self.value.shape)

    @property
    def ndim(self):
        return self.value.ndim

    def dim(self):
        return self.value.ndim

    def rank(self):
        return self.value.ndim

    @property
    def size(self):
        return int(np.prod(self.value.shape)) if self.value.shape else 1

    @property
    def dtype(self):
        return jnp.dtype(self.value.dtype)

    @property
    def place(self):
        return device_mod.current_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        return _ops.t(self)

    @property
    def mT(self):
        return _ops.matrix_transpose(self)

    def numel(self):
        return self.size

    def element_size(self):
        return np.dtype(self.dtype).itemsize

    def is_floating_point(self):
        return dtypes_mod.is_floating_point_dtype(self.dtype)

    # ------------------------------------------------------------- convert
    def numpy(self):
        return np.asarray(self.value)

    def item(self, *args):
        self._guard_concrete(".item()")
        arr = np.asarray(self.value)
        return arr.item(*args)

    def tolist(self):
        return np.asarray(self.value).tolist()

    def astype(self, dtype):
        return _ops.cast(self, dtype)

    def cast(self, dtype):
        return _ops.cast(self, dtype)

    def cpu(self):
        cpu_dev = device_mod.jax_device(device_mod.Place("cpu", 0))
        return Tensor(jax.device_put(self.value, cpu_dev), self.stop_gradient)

    def cuda(self, device_id=None, blocking=True):
        """Move to the accelerator (reference Tensor.cuda; here: the
        default non-CPU device — TPU)."""
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        if not accel:
            return self  # CPU-only environment: no-op (tests/CI)
        dev = accel[device_id or 0] if device_id is not None else accel[0]
        return Tensor(jax.device_put(self.value, dev), self.stop_gradient)

    def ndimension(self):
        return self.ndim

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and (
                a.startswith(("cpu", "tpu", "gpu")) or ":" in a
            ):
                dev = device_mod.jax_device(_parse_place(a))
                out = Tensor(jax.device_put(out.value, dev), out.stop_gradient)
            elif a is not None:
                out = out.astype(a)
        return out

    def pin_memory(self):  # host-staging is XLA-managed; API parity no-op
        return self

    def contiguous(self):  # jax arrays are always logically contiguous
        return self

    def is_contiguous(self):
        return True

    # ------------------------------------------------------------ autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd.backward import run_backward

        run_backward(self, grad_tensor, retain_graph)

    def detach(self):
        t = Tensor(self.value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return _ops.assign(self)

    def register_hook(self, hook):
        """Run ``hook(grad)`` when this tensor's cotangent is computed.

        If the hook returns a value it replaces the gradient (paddle parity).
        """
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Handle:
            def __init__(h, hooks, fn):
                h._hooks, h._fn = hooks, fn

            def remove(h):
                if h._fn in h._hooks:
                    h._hooks.remove(h._fn)

        return _Handle(self._hooks, hook)

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad.value))
        else:
            self.grad = None

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # ------------------------------------------------------------- mutation
    def set_value(self, value):
        """In-place value replacement (paddle Tensor.set_value parity)."""
        if isinstance(value, Tensor):
            value = value.value
        arr = jnp.asarray(value)
        if tuple(arr.shape) != tuple(self.value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self.value.shape}"
            )
        self.value = arr.astype(self.value.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, v):
        self.value = jnp.full_like(self.value, v)
        return self

    def zero_(self):
        self.value = jnp.zeros_like(self.value)
        return self

    def _replace_with(self, other: "Tensor"):
        """Adopt another tensor's value + autograd identity (inplace ops)."""
        import weakref

        self.value = other.value
        self._node = other._node
        self._out_idx = other._out_idx
        self.stop_gradient = other.stop_gradient
        if self._node is not None:
            # the graph's output edge must track *this* object now
            self._node.out_refs[self._out_idx] = weakref.ref(self)
        return self

    def _alias_for_inplace(self):
        """Snapshot this tensor's graph identity before an in-place op.

        The alias becomes the recorded *input* of the in-place op (and takes
        over as the producer node's tracked output), so pre-mutation history
        stays reachable while ``self`` moves on to the new node. Without
        this, x[i]=v would make x input and output of its own GradNode and
        sever the upstream graph.
        """
        import weakref

        a = Tensor(self.value, self.stop_gradient, name=self.name)
        a._node = self._node
        a._out_idx = self._out_idx
        if a._node is not None:
            a._node.out_refs[a._out_idx] = weakref.ref(a)
        return a

    def _inplace(self, op, *args, **kw):
        alias = self._alias_for_inplace()
        return self._replace_with(op(alias, *args, **kw))

    # ------------------------------------------------------------- dunders
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.value.shape[0]

    def _guard_concrete(self, what):
        import jax as _jax

        if isinstance(self.value, _jax.core.Tracer):
            raise TypeError(
                f"{what} of a traced Tensor: inside to_static/jit the "
                "value is not available, so data-dependent Python control "
                "flow cannot be compiled. to_static auto-converts "
                "`if`/`elif`/`while`/`for i in range(...)` on Tensor "
                "conditions, including early return/break/continue "
                "inside them — but only when the function's source is "
                "importable (defined in a file, not a REPL) and the "
                "exit does not escape a try/except or a generator. "
                "Otherwise use paddle.static.nn.cond / while_loop / "
                "switch_case, or express the branch as a select with "
                "paddle.where. (reference: dy2static unsupported-syntax "
                "errors)"
            )

    def __bool__(self):
        self._guard_concrete("bool()")
        return bool(np.asarray(self.value))

    def __int__(self):
        self._guard_concrete("int()")
        return int(np.asarray(self.value))

    def __float__(self):
        self._guard_concrete("float()")
        return float(np.asarray(self.value))

    def __index__(self):
        self._guard_concrete("index()")
        return int(np.asarray(self.value))

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __repr__(self):
        try:
            data = np.asarray(self.value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:  # inside a jit trace
            body = f"<traced {self.value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={np.dtype(self.dtype).name}, "
            f"place={self.place}, stop_gradient={self.stop_gradient},\n"
            f"       {body})"
        )

    # math dunders dispatch through the ops namespace (autograd-aware)
    def __add__(self, o):
        return _ops.add(self, o)

    def __radd__(self, o):
        return _ops.add(o, self)

    def __sub__(self, o):
        return _ops.subtract(self, o)

    def __rsub__(self, o):
        return _ops.subtract(o, self)

    def __mul__(self, o):
        return _ops.multiply(self, o)

    def __rmul__(self, o):
        return _ops.multiply(o, self)

    def __truediv__(self, o):
        return _ops.divide(self, o)

    def __rtruediv__(self, o):
        return _ops.divide(o, self)

    def __floordiv__(self, o):
        return _ops.floor_divide(self, o)

    def __mod__(self, o):
        return _ops.mod(self, o)

    def __pow__(self, o):
        return _ops.pow(self, o)

    def __rpow__(self, o):
        return _ops.pow(o, self)

    def __neg__(self):
        return _ops.neg(self)

    def __abs__(self):
        return _ops.abs(self)

    def __matmul__(self, o):
        return _ops.matmul(self, o)

    def __rmatmul__(self, o):
        return _ops.matmul(o, self)

    def __eq__(self, o):
        return _ops.equal(self, o)

    def __ne__(self, o):
        return _ops.not_equal(self, o)

    def __lt__(self, o):
        return _ops.less_than(self, o)

    def __le__(self, o):
        return _ops.less_equal(self, o)

    def __gt__(self, o):
        return _ops.greater_than(self, o)

    def __ge__(self, o):
        return _ops.greater_equal(self, o)

    def __invert__(self):
        return _ops.logical_not(self)

    def __and__(self, o):
        return _ops.logical_and(self, o)

    def __or__(self, o):
        return _ops.logical_or(self, o)

    def __xor__(self, o):
        return _ops.logical_xor(self, o)

    def __getitem__(self, idx):
        return _ops.getitem(self, idx)

    def __setitem__(self, idx, v):
        self._inplace(_ops.setitem, idx, v)

    # numpy protocol — lets np.asarray(tensor) work
    def __array__(self, dtype=None):
        arr = np.asarray(self.value)
        return arr.astype(dtype) if dtype is not None else arr


def _parse_place(s: str) -> device_mod.Place:
    s = s.lower()
    if s.startswith("gpu"):
        s = "tpu" + s[3:]
    if ":" in s:
        kind, _, idx = s.partition(":")
        return device_mod.Place(kind, int(idx))
    return device_mod.Place(s, 0)


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/framework Parameter —
    unverified). stop_gradient defaults False; optimizers discover these via
    Layer.parameters()."""

    __slots__ = (
        "trainable",
        "optimize_attr",
        "regularizer",
        "is_distributed",
        "need_clip",
        "split_axis",
        "sequence_parallel",
        "_lazy_initializer",  # set under LazyGuard; see Layer.materialize
        "_lazy_seq",  # creation-order ticket for materialize() RNG replay
    )

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        self.split_axis = None  # set by TP layers (mp partition axis)
        self.sequence_parallel = False  # set by SP's mark_as_... helper
        self.persistable = True


def is_tensor(obj) -> bool:
    return isinstance(obj, Tensor)


# jax pytree registration: Tensors flatten to their underlying array so whole
# models/state dicts can cross jit boundaries untouched.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t.value,), t.stop_gradient),
    lambda sg, vals: Tensor(vals[0], stop_gradient=sg),
)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t.value,), t.trainable),
    lambda tr, vals: Parameter(vals[0], trainable=tr),
)
