"""Forward-API shims for older jax runtimes.

The framework targets the modern jax surface (``jax.shard_map`` with
``check_vma``/``axis_names`` — pyproject floors at jax>=0.9), but some
deployment images pin older jax lines where that spelling does not
exist yet (0.4.x ships ``jax.experimental.shard_map.shard_map`` with
``check_rep``/``auto``). This module installs a translating alias ONCE
at package import when — and only when — the modern name is missing, so
every internal call site keeps the single modern spelling:

- ``check_vma=`` maps to ``check_rep=`` (same meaning, renamed
  upstream);
- ``axis_names={...}`` (manual over a SUBSET of mesh axes) has no safe
  legacy equivalent: 0.4.x's experimental ``auto=`` miscompiles or
  hard-aborts the process on the nested-shard_map programs this
  framework builds (ring attention inside the compiled pipeline), so
  the alias REFUSES partial-manual requests with a clear
  NotImplementedError instead — a clean per-test failure on old
  images, never a crashed interpreter.

On a jax that already has ``jax.shard_map`` this module is a no-op.
"""
from __future__ import annotations

import jax


def _install_shard_map_alias():
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, axis_names=None,
                  auto=None, **kw):
        rep = check_rep if check_rep is not None else check_vma
        kwargs = dict(kw)
        if auto is None and axis_names is not None:
            auto = frozenset(
                getattr(mesh, "axis_names", ())
            ) - frozenset(axis_names)
        if auto:
            raise NotImplementedError(
                "partial-manual shard_map (axis_names/auto over a "
                "subset of mesh axes) requires jax >= 0.6; this legacy "
                "runtime only supports manual-over-all-axes shard_map"
            )
        if rep is not None:
            kwargs["check_rep"] = rep
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **kwargs,
        )

    shard_map._paddle_tpu_legacy_alias = True
    jax.shard_map = shard_map


if not hasattr(jax, "shard_map"):  # pragma: no branch
    _install_shard_map_alias()


def partial_manual_shard_map_supported() -> bool:
    """Whether this jax supports manual-over-a-SUBSET shard_map
    (axis_names=...). False on 0.4.x images where the alias above
    refuses it — callers (compiled pipeline lowering proofs, ring
    attention benches, their tests) degrade to GSPMD-only reduced modes
    there instead of failing mid-trace."""
    return not getattr(jax.shard_map, "_paddle_tpu_legacy_alias", False)
