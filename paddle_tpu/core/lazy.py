"""Lazy (abstract) parameter initialization — ``paddle.LazyGuard``.

Reference parity: ``paddle.LazyGuard`` (python/paddle/nn/initializer/
lazy_init.py — unverified, mount empty) lets users build models far too
large for one host's memory by deferring parameter materialization.

TPU-first design: instead of the reference's "record the init program,
replay later" machinery, a lazy parameter's ``.value`` is a
``jax.ShapeDtypeStruct`` — the exact currency of XLA's ahead-of-time
path. A lazily-built model can be traced, sharded, and LOWERED to
StableHLO (``jax.jit(...).lower`` accepts abstract leaves) without a
single weight byte existing anywhere: that is how the Llama-2-7B hybrid
program is compile-proven on an 8-device virtual mesh (tools/lower_7b.py)
on a host that could never hold 7B fp32 params + Adam state.

Materialization, when wanted, goes through the sharding-aware
initializers at ``device_put`` time (each shard initialized on its own
chip), not through a host-resident full tensor.
"""
from __future__ import annotations

import contextlib

_LAZY = [False]
_SEQ = [0]


def in_lazy_mode() -> bool:
    return _LAZY[0]


def next_seq() -> int:
    """Monotone creation-order ticket for lazy parameters (materialize
    replays initializers in this order so the RNG stream matches eager
    init exactly)."""
    _SEQ[0] += 1
    return _SEQ[0]


class LazyGuard(contextlib.AbstractContextManager):
    """Context manager: parameters created inside hold abstract values.

    Example::

        with paddle.LazyGuard():
            net = LlamaForCausalLMPipe(LlamaConfig.llama2_7b())
        # net.parameters() hold ShapeDtypeStructs; jit(...).lower works
    """

    def __enter__(self):
        self._prev = _LAZY[0]
        _LAZY[0] = True
        return self

    def __exit__(self, *exc):
        _LAZY[0] = self._prev
        return False


def abstract_like(shape, dtype, sharding=None):
    import jax

    if sharding is not None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def is_abstract(value) -> bool:
    import jax

    return isinstance(value, jax.ShapeDtypeStruct)
