"""Flight recorder: a bounded ring of step records that survives crashes.

A training run that dies at step 80_000 with a NaN loss tells you
nothing unless someone was watching the dashboards at the time. The
flight recorder is the black box: every optimizer step appends a small
host-side record (timings, throughput, memory high-water, lazy loss
ref) to a ring buffer of the last K steps; trace-guard fires and other
notable events land in a second bounded ring. On a crash — an uncaught
exception, or the ``FLAGS_check_nan_inf`` sweep detecting a non-finite
op output — the recorder dumps one JSON bundle: the step ring, the
event ring, a full registry snapshot, and environment info. The bundle
is also available on demand (:meth:`FlightRecorder.dump`) and over the
``/flight`` HTTP endpoint.

Hook installation is explicit (:meth:`install`): it chains
``sys.excepthook`` (dump, then defer to the previous hook) and arms the
NaN hook seam in ``core.dispatch._nan_report`` — the same machinery the
recompute/check_nan_inf tests exercise — so the bundle is written
BEFORE the RuntimeError propagates. ``watch()`` is the scoped variant
for drivers that own their try/except.

Lazy values (device-scalar losses held by gauges/records) are
materialized at dump time only; a dump is the one place a device sync
is acceptable — the process is dying anyway.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback

from .registry import (
    get_registry,
    nonblocking_active,
    nonblocking_values,
    value_is_ready,
)

DEFAULT_CAPACITY = 64


def _jsonable(v):
    """Best-effort scalar materialization for bundle serialization:
    callables invoked, device/numpy scalars fetched (repr'd instead
    when still in flight under ``nonblocking_values``), else repr'd."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        if callable(v):
            v = v()
        # under nonblocking_values an in-flight device value is repr'd;
        # a normal dump blocks a moment and reports the number
        if nonblocking_active() and not value_is_ready(v):
            return repr(v)
        import numpy as np

        return float(np.asarray(v))
    except Exception:
        return repr(v)


class FlightRecorder:
    """Bounded ring buffer of step records + crash-dumping hooks."""

    def __init__(self, capacity=DEFAULT_CAPACITY, registry=None,
                 dump_dir=None, event_capacity=256):
        self.capacity = int(capacity)
        self.registry = registry or get_registry()
        self.dump_dir = dump_dir or os.environ.get(
            "PADDLE_TPU_FLIGHT_DIR", "."
        )
        self._ring = collections.deque(maxlen=self.capacity)
        self._events = collections.deque(maxlen=int(event_capacity))
        self._lock = threading.Lock()
        self._installed = False
        self._prev_excepthook = None
        self._dump_count = 0
        self.last_dump_path = None
        # name -> zero-arg callable; each contributes one bundle section
        # (e.g. the SLO monitor's active alerts + recent window samples)
        self._sections = {}

    # ------------------------------------------------------------ feeding
    def record_step(self, record):
        """Append one step record (a small plain dict; values may be
        lazy — they materialize at dump time)."""
        with self._lock:
            self._ring.append(record)

    def note(self, kind, **info):
        """Append a notable event (guard fire, scale skip, restart...)."""
        ev = {"kind": str(kind), "time": time.time()}
        ev.update(info)
        with self._lock:
            self._events.append(ev)

    def steps(self):
        with self._lock:
            return list(self._ring)

    def events(self):
        with self._lock:
            return list(self._events)

    def add_section(self, name, fn):
        """Register a provider whose ``fn()`` output lands under
        ``bundle()['sections'][name]``. Replace-on-register, matching
        the metrics registry: the newest owner of a name wins (an
        engine reload re-attaching its monitor must not stack stale
        providers)."""
        with self._lock:
            self._sections[str(name)] = fn

    def remove_section(self, name):
        with self._lock:
            self._sections.pop(str(name), None)

    # ------------------------------------------------------------ dumping
    def bundle(self, reason="on_demand", exc=None, sync=True):
        """The diagnostic bundle as a plain dict (lazy values
        materialized here — the only place a device sync is allowed).

        ``sync=False`` is the NaN-hook mode: the dump runs INSIDE a
        ``jax.debug.callback`` while the compiled step is still
        executing, so fetching an in-flight device ref (this very
        step's loss) would deadlock — not-ready values are repr'd /
        skipped instead of fetched."""
        if not sync:
            with nonblocking_values():
                return self.bundle(reason=reason, exc=exc, sync=True)
        with self._lock:
            steps = [dict(r) for r in self._ring]
            events = [dict(e) for e in self._events]
            providers = list(self._sections.items())
        sections = {}
        for sec_name, fn in providers:
            # a broken provider must never take the crash dump with it
            try:
                sections[sec_name] = fn()
            except Exception as e:  # pragma: no cover - defensive
                sections[sec_name] = {"error": repr(e)}
        info = {"python": sys.version.split()[0]}
        try:
            import jax

            info["jax"] = jax.__version__
            devs = jax.local_devices()
            info["devices"] = [
                f"{d.platform}:{d.id}:{getattr(d, 'device_kind', '?')}"
                for d in devs
            ]
            info["process_index"] = jax.process_index()
            info["process_count"] = jax.process_count()
        except Exception:
            pass
        exc_info = None
        if exc is not None:
            exc_info = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(
                    traceback.format_exception(
                        type(exc), exc, exc.__traceback__
                    )
                ),
            }
        try:
            registry_snap = self.registry.snapshot()
        except Exception:
            registry_snap = {}
        # name the requests in flight: a NaN/watchdog bundle that says
        # WHICH traces were mid-decode turns "something was running"
        # into a /trace (or serve_bench --trace-out) lookup
        try:
            from .tracing import get_tracer

            tracer = get_tracer()
            traces_in_flight = tracer.active_trace_ids()
            spans_in_flight = tracer.active_spans()
        except Exception:
            traces_in_flight, spans_in_flight = [], []
        return _jsonable({
            "reason": reason,
            "time": time.time(),
            "capacity": self.capacity,
            "exception": exc_info,
            "steps": steps,
            "events": events,
            "traces_in_flight": traces_in_flight,
            "spans_in_flight": spans_in_flight,
            "sections": sections,
            "registry": registry_snap,
            "env": info,
        })

    def dump(self, path=None, reason="on_demand", exc=None, sync=True):
        """Write the bundle as JSON; returns the path written."""
        bundle = self.bundle(reason=reason, exc=exc, sync=sync)
        if path is None:
            with self._lock:
                self._dump_count += 1
                n = self._dump_count
            path = os.path.join(
                self.dump_dir,
                f"flight_{os.getpid()}_{n}.json",
            )
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        self.last_dump_path = path
        return path

    # -------------------------------------------------------------- hooks
    def _on_nan(self, op_name):
        self.note("naninf", op=str(op_name))
        try:
            # sync=False: on traced paths this hook runs inside a
            # jax.debug.callback while the step executes — blocking on
            # its own in-flight refs would deadlock instead of dumping
            self.dump(reason=f"naninf:{op_name}", sync=False)
        except Exception:
            pass

    def _excepthook(self, etype, evalue, etb):
        try:
            if evalue is not None and evalue.__traceback__ is None:
                evalue = evalue.with_traceback(etb)
            self.dump(reason="uncaught_exception", exc=evalue)
        except Exception:
            pass
        prev = self._prev_excepthook or sys.__excepthook__
        prev(etype, evalue, etb)

    def install(self, nan_hook=True, excepthook=True):
        """Arm the crash hooks. Chained, not clobbered: the previous
        ``sys.excepthook`` still runs after the dump."""
        if self._installed:
            return self
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if nan_hook:
            from ..core import dispatch

            dispatch._NANINF_HOOK[0] = self._on_nan
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        from ..core import dispatch

        if dispatch._NANINF_HOOK[0] is self._on_nan:
            dispatch._NANINF_HOOK[0] = None
        self._installed = False

    def watch(self, reason="exception"):
        """Scoped crash capture::

            with recorder.watch():
                train()   # any exception dumps a bundle, then re-raises
        """
        recorder = self

        class _Watch:
            def __enter__(self):
                return recorder

            def __exit__(self, etype, evalue, etb):
                if etype is not None:
                    try:
                        recorder.dump(
                            reason=f"watch:{reason}", exc=evalue
                        )
                    except Exception:
                        pass
                return False

        return _Watch()

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._events.clear()


# ------------------------------------------------------- process default
_DEFAULT = [None]
_DEFAULT_LOCK = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    with _DEFAULT_LOCK:
        if _DEFAULT[0] is None:
            _DEFAULT[0] = FlightRecorder(
                capacity=int(os.environ.get(
                    "PADDLE_TPU_FLIGHT_CAPACITY", DEFAULT_CAPACITY
                ))
            )
        return _DEFAULT[0]


def set_flight_recorder(recorder):
    with _DEFAULT_LOCK:
        prev, _DEFAULT[0] = _DEFAULT[0], recorder
    return prev
