"""Multihost aggregation: per-host registries merged into one report.

Each process owns its registry (metrics are process-local by
construction — a TPU pod's host 3 cannot observe host 0's queue
depths). The merge path: every host takes a :func:`tagged_snapshot`
(its registry snapshot stamped with process index/count + hostname),
the snapshots travel through the existing distributed layer
(``distributed.communication.all_gather_object`` — the same
pickle+allgather seam checkpointing uses), and :func:`merge_snapshots`
folds them into one report:

- counters: values and label series SUM across hosts;
- gauges: per-host values kept (keyed by process index) plus
  min/max/mean — a gauge mean hides stragglers, so the spread stays;
- histograms: exact ``count``/``sum`` and bucket counts sum (they are
  running totals, so the merge is exact); percentiles are re-derived
  from the merged cumulative buckets (bucket-resolution approximation —
  per-host exact percentiles are kept under ``per_host``).

Single-process runs (the CPU CI, `tools/vmesh.py` virtual meshes) take
the same path with a one-element gather, so ``merged_report()`` is safe
to call unconditionally at the end of any run.
"""
from __future__ import annotations

import math
import socket


def tagged_snapshot(registry=None):
    """This host's registry snapshot stamped with process identity."""
    from .registry import get_registry

    snap = (registry or get_registry()).snapshot()
    try:
        from ..distributed import env as dist_env

        snap["process_index"] = dist_env.get_rank()
        snap["process_count"] = dist_env.get_world_size()
    except Exception:
        snap["process_index"] = 0
        snap["process_count"] = 1
    try:
        snap["host"] = socket.gethostname()
    except Exception:
        snap["host"] = "unknown"
    return snap


def _percentile_from_buckets(buckets, p):
    """Nearest-bucket-upper-bound percentile from cumulative buckets
    ``[{"le": ub, "count": c}, ...]`` (resolution = bucket width)."""
    if not buckets:
        return None
    total = buckets[-1]["count"]
    if total <= 0:
        return None
    rank = p / 100.0 * total
    for b in buckets:
        if b["count"] >= rank:
            le = b["le"]
            return None if (isinstance(le, float) and math.isinf(le)) \
                else le
    return None


def merge_snapshots(snapshots):
    """Fold tagged per-host snapshots into one merged report."""
    hosts = [
        {
            "process_index": s.get("process_index", i),
            "host": s.get("host", "unknown"),
        }
        for i, s in enumerate(snapshots)
    ]
    merged = {}
    for i, snap in enumerate(snapshots):
        pidx = snap.get("process_index", i)
        for name, d in snap.get("metrics", {}).items():
            kind = d.get("type", "untyped")
            m = merged.setdefault(name, {
                "type": kind, "help": d.get("help", ""),
                "unit": d.get("unit", ""),
            })
            if kind == "counter":
                m["value"] = m.get("value", 0) + d.get("value", 0)
                series = m.setdefault("series", {})
                for s in d.get("series", []):
                    key = tuple(sorted(s["labels"].items()))
                    series[key] = series.get(key, 0) + s["value"]
            elif kind == "gauge":
                per = m.setdefault("per_host", {})
                for s in d.get("series", []):
                    key = tuple(sorted(s["labels"].items()))
                    per.setdefault(key, {})[pidx] = s["value"]
            elif kind == "histogram":
                m["count"] = m.get("count", 0) + d.get("count", 0)
                m["sum"] = m.get("sum", 0.0) + d.get("sum", 0.0)
                bks = m.setdefault("_buckets", {})
                for b in d.get("buckets", []):
                    le = float(b["le"])
                    bks[le] = bks.get(le, 0) + b["count"]
                m.setdefault("per_host", {})[pidx] = {
                    k: d.get(k) for k in
                    ("count", "sum", "mean", "p50", "p90", "p99",
                     "window_count")
                }
    # finalize: label keys back to dicts, gauge spread, histogram pcts
    out = {"hosts": hosts, "metrics": {}}
    for name, m in merged.items():
        kind = m["type"]
        fin = {"type": kind, "help": m.get("help", "")}
        if m.get("unit"):
            fin["unit"] = m["unit"]
        if kind == "counter":
            fin["value"] = m.get("value", 0)
            fin["series"] = [
                {"labels": dict(k), "value": v}
                for k, v in sorted(m.get("series", {}).items())
            ]
        elif kind == "gauge":
            fin["series"] = []
            for key, per in sorted(m.get("per_host", {}).items()):
                vals = [v for v in per.values()
                        if isinstance(v, (int, float))]
                entry = {
                    "labels": dict(key),
                    "per_host": {str(k): v for k, v in per.items()},
                }
                if vals:
                    entry.update(
                        min=min(vals), max=max(vals),
                        mean=sum(vals) / len(vals),
                    )
                fin["series"].append(entry)
        elif kind == "histogram":
            count = m.get("count", 0)
            total = m.get("sum", 0.0)
            fin["count"] = count
            fin["sum"] = total
            fin["mean"] = (total / count) if count else None
            buckets = [
                {"le": le, "count": c}
                for le, c in sorted(m.get("_buckets", {}).items())
            ]
            fin["buckets"] = buckets
            for p in (50, 90, 99):
                fin[f"p{p}"] = _percentile_from_buckets(buckets, p)
            fin["per_host"] = {
                str(k): v for k, v in m.get("per_host", {}).items()
            }
        out["metrics"][name] = fin
    return out


def merged_report(registry=None, group=None):
    """Gather every host's tagged snapshot through the distributed layer
    and merge. Falls back to the local snapshot when the process is not
    part of a multi-process world (CI, vmesh subprocesses, notebooks)."""
    local = tagged_snapshot(registry)
    world = local.get("process_count", 1)
    if world <= 1:
        return merge_snapshots([local])
    try:
        from ..distributed import communication as comm

        gathered = []
        comm.all_gather_object(gathered, local, group=group)
        if not gathered:
            gathered = [local]
    except Exception:
        gathered = [local]
    return merge_snapshots(gathered)
