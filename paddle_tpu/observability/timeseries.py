"""Bounded in-process time series over cumulative metrics.

The metrics registry is deliberately point-in-time: counters and
histogram bucket counts only ever grow, and a scrape sees one instant.
Burn-rate alerting and attainment dashboards need the other axis —
"what happened over the last minute" — without an external Prometheus.
:class:`TimeSeriesRing` is that axis: a fixed-capacity ring of
``(t, {key: float})`` samples appended on a background interval, with
windowed delta/rate readers that tolerate counter resets (an engine
reload re-registers fresh metrics, so a cumulative series can step
DOWN; a reset-naive ``last - first`` would go negative and a dashboard
would show a physically impossible rate).

Memory is bounded by construction: ``capacity`` samples, each a flat
dict of floats. No wall-clock calls happen inside the ring — the caller
supplies every timestamp — so tests drive it with a fake clock exactly
like ``autotune``'s timer discipline.
"""

from __future__ import annotations

import threading


class TimeSeriesRing:
    """Fixed-capacity ring of ``(t, values)`` samples with windowed
    readers.

    ``values`` is a flat ``{key: float}`` dict; keys may come and go
    between samples (a class with no traffic yet simply has no series).
    All readers take an explicit ``now`` (default: the latest sample's
    timestamp) so the ring itself never consults a clock."""

    def __init__(self, capacity=512):
        if int(capacity) < 2:
            raise ValueError("TimeSeriesRing needs capacity >= 2")
        self.capacity = int(capacity)
        self._buf = [None] * self.capacity
        self._head = 0  # next write slot
        self._len = 0
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return self._len

    def append(self, t, values):
        """Record one sample. ``values`` is copied (floats coerced) so
        the caller may reuse its dict."""
        snap = {str(k): float(v) for k, v in values.items()}
        with self._lock:
            self._buf[self._head] = (float(t), snap)
            self._head = (self._head + 1) % self.capacity
            self._len = min(self._len + 1, self.capacity)

    def _ordered(self):
        # oldest -> newest; caller must hold the lock
        if self._len < self.capacity:
            return self._buf[: self._len]
        return self._buf[self._head:] + self._buf[: self._head]

    def last(self, k=1):
        """The most recent ``k`` samples, oldest first, as
        ``[(t, values)]`` copies."""
        with self._lock:
            tail = self._ordered()[-int(k):]
            return [(t, dict(v)) for t, v in tail]

    def window(self, window_s=None, now=None):
        """Samples inside ``[now - window_s, now]`` plus ONE sample just
        before the window start when available — the baseline that makes
        a windowed delta cover the full span instead of starting at the
        first in-window sample."""
        with self._lock:
            ordered = [(t, dict(v)) for t, v in self._ordered()]
        if not ordered:
            return []
        if now is None:
            now = ordered[-1][0]
        if window_s is None:
            return [s for s in ordered if s[0] <= now]
        lo = float(now) - float(window_s)
        out, baseline = [], None
        for s in ordered:
            if s[0] > now:
                continue
            if s[0] < lo:
                baseline = s
            else:
                out.append(s)
        if baseline is not None:
            out.insert(0, baseline)
        return out

    def series(self, key, window_s=None, now=None):
        """``[(t, value)]`` for one key over the window, skipping
        samples where the key is absent."""
        key = str(key)
        return [
            (t, v[key]) for t, v in self.window(window_s, now) if key in v
        ]

    def delta(self, key, window_s=None, now=None):
        """Counter-reset-tolerant increase of a cumulative series over
        the window: the sum of POSITIVE step-wise deltas. A step down
        (engine reload re-registering the metric at zero) contributes
        nothing instead of a negative spike. 0.0 with < 2 points."""
        pts = self.series(key, window_s, now)
        if len(pts) < 2:
            return 0.0
        total = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            if b > a:
                total += b - a
        return total

    def rate(self, key, window_s=None, now=None):
        """``delta / elapsed`` per second over the window's actual span
        (first to last in-window point, not the nominal window — the
        ring may hold less history than asked for). 0.0 with < 2 points
        or zero span."""
        pts = self.series(key, window_s, now)
        if len(pts) < 2:
            return 0.0
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return 0.0
        return self.delta(key, window_s, now) / span

    def latest(self, key, default=None):
        """Most recent value of ``key`` (gauge read), or ``default``."""
        key = str(key)
        with self._lock:
            ordered = self._ordered()
            for t, v in reversed(ordered):
                if key in v:
                    return v[key]
        return default
