"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Every observability surface the repo has grown — serving's
TTFT/ITL/queue counters, the trace guard's recompile-storm fires, the
profiler's lint-event counts, and (new) training-step telemetry — used
to keep private state with private readouts. This module is the one
place they all publish into: a named instrument registers itself in a
:class:`MetricsRegistry` and every consumer (the Prometheus text
exporter, the JSON snapshot, the /metrics HTTP endpoint, the flight
recorder's crash bundle, the multihost merge) reads the same registry.

Design constraints, in order:

- **Never on the device.** Observing is a host-side integer/float
  update under a lock. Gauges may hold a CALLABLE (or a jax device
  scalar) that is materialized only when somebody scrapes — the fit hot
  loop must not synchronize with the device per step (hapi's lazy-logs
  rule applies here too).
- **Bounded memory.** Counters/gauges are O(label cardinality);
  histograms keep a fixed running bucket vector plus a bounded sliding
  sample window (see :class:`Histogram` for the mean-vs-percentile
  window split).
- **Replace-on-register.** Re-constructing an instrument set (a fresh
  ``ServingMetrics`` per engine, a bench resetting after warmup)
  re-registers under the same name and REPLACES the previous series —
  the registry always reflects the newest owner, and tests stay
  isolated without global resets.
"""
from __future__ import annotations

import bisect
import collections
import threading

# latency-shaped default buckets (seconds)
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# count-shaped buckets (queue depths, slot occupancy)
COUNT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                 256.0, 1024.0)
# token-batch-shaped buckets: B*S for real LLM steps runs well past 4k
# (the repo's own perf config is 4x1024); powers of four up to ~1M
TOKEN_BUCKETS = (16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0,
                 262144.0, 1048576.0)


def _labels_key(labels):
    return tuple(sorted(labels.items()))


class _Metric:
    """Shared identity: ``name`` is the short/display name, ``prom_name``
    the canonical registry + Prometheus series name."""

    metric_type = "untyped"

    def __init__(self, name, help="", unit="", prom_name=None):
        self.name = name
        self.prom_name = prom_name or name
        self.help = help
        self.unit = unit
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter with an optional label breakdown.

    ``inc(n, **labels)`` always bumps the unlabeled total; when labels
    are given the matching child series is bumped as well, so the total
    never needs a sum over children at read time."""

    metric_type = "counter"

    def __init__(self, name, help="", unit="", prom_name=None):
        super().__init__(name, help=help, unit=unit, prom_name=prom_name)
        self._value = 0
        self._series = {}
        # bounded per-series exemplar: the LAST trace_id whose request
        # bumped this series (labels_key -> {"trace_id", "value"}) —
        # same cardinality bound as the series map itself
        self._exemplars = {}

    def inc(self, n=1, trace_id=None, **labels):
        with self._lock:
            self._value += n
            k = _labels_key(labels)
            if labels:
                self._series[k] = self._series.get(k, 0) + n
            if trace_id is not None:
                self._exemplars[k] = {
                    "trace_id": str(trace_id), "value": float(n),
                }

    def labels(self, **labels):
        counter = self

        class _Bound:
            def inc(self, n=1, trace_id=None):
                counter.inc(n, trace_id=trace_id, **labels)

        return _Bound()

    @property
    def value(self):
        return self._value

    def series(self):
        with self._lock:
            return dict(self._series)

    def exemplars(self):
        """labels_key -> {"trace_id", "value"} (copies)."""
        with self._lock:
            return {k: dict(v) for k, v in self._exemplars.items()}

    def data(self):
        with self._lock:
            out = {
                "type": self.metric_type,
                "value": self._value,
                "series": [
                    dict(
                        {"labels": dict(k), "value": v},
                        **({"exemplar": dict(self._exemplars[k])}
                           if k in self._exemplars else {}),
                    )
                    for k, v in self._series.items()
                ],
            }
            ex = self._exemplars.get(())
            if ex is not None:
                out["exemplar"] = dict(ex)
            return out


_NONBLOCK = threading.local()


def nonblocking_active():
    """True inside a :class:`nonblocking_values` context (the one
    public check — callers must not reach into the thread-local)."""
    return getattr(_NONBLOCK, "on", False)


class nonblocking_values:
    """Context: lazy-value materialization must not block.

    A crash dump fired from inside a ``jax.debug.callback`` (the NaN
    hook) runs WHILE the compiled step executes; fetching a device ref
    of that very computation would deadlock the process instead of
    dumping. Inside this context, values whose ``is_ready()`` reports
    false are skipped (gauges) or repr'd (flight records) rather than
    fetched. Thread-local, so a concurrent normal scrape on another
    thread keeps its blocking lazy semantics."""

    def __enter__(self):
        self._prev = getattr(_NONBLOCK, "on", False)
        _NONBLOCK.on = True
        return self

    def __exit__(self, *exc):
        _NONBLOCK.on = self._prev
        return False


def value_is_ready(v):
    """False only when ``v`` is an in-flight device value (jax Array
    with ``is_ready() == False``); anything else counts as ready."""
    ready = getattr(v, "is_ready", None)
    if callable(ready):
        try:
            return bool(ready())
        except Exception:
            return True
    return True


def _materialize(v):
    """Resolve a lazy gauge value: callables are invoked, device scalars
    fetched — only ever on the scrape path, never per step. Under
    :class:`nonblocking_values`, an in-flight device value raises
    instead of blocking (the caller skips the series)."""
    if callable(v):
        v = v()
    if nonblocking_active() and not value_is_ready(v):
        raise ValueError("device value still in flight "
                         "(nonblocking scrape)")
    try:
        return float(v)
    except (TypeError, ValueError):
        import numpy as np

        return float(np.asarray(v))


class Gauge(_Metric):
    """Last-value instrument. ``set`` accepts a float, a callable, or a
    device scalar; lazy values materialize on scrape (snapshot /
    Prometheus render), keeping the training hot loop sync-free."""

    metric_type = "gauge"

    def __init__(self, name, help="", unit="", prom_name=None):
        super().__init__(name, help=help, unit=unit, prom_name=prom_name)
        self._series = {}  # labels_key -> value | callable | device ref

    def set(self, value, **labels):
        with self._lock:
            self._series[_labels_key(labels)] = value

    def set_function(self, fn, **labels):
        self.set(fn, **labels)

    def inc(self, n=1.0, **labels):
        with self._lock:
            k = _labels_key(labels)
            cur = self._series.get(k, 0.0)
            if callable(cur):
                raise TypeError(f"gauge {self.name}: inc() on a lazy value")
            self._series[k] = cur + n

    def dec(self, n=1.0, **labels):
        self.inc(-n, **labels)

    def value(self, **labels):
        with self._lock:
            v = self._series.get(_labels_key(labels))
        return None if v is None else _materialize(v)

    def data(self):
        with self._lock:
            items = list(self._series.items())
        series = []
        for k, v in items:
            try:
                series.append({"labels": dict(k), "value": _materialize(v)})
            except Exception:
                continue  # a lazy value that cannot resolve is skipped
        return {"type": self.metric_type, "series": series}


class _HistogramChild:
    """Per-label-set running state of a labeled :class:`Histogram`
    (cumulative count/sum/buckets plus a small sliding window for
    per-label percentiles). Mutated only under the parent's lock."""

    __slots__ = ("count", "sum", "bucket_counts", "exemplars", "window")

    def __init__(self, nslots, window_maxlen):
        self.count = 0
        self.sum = 0.0
        self.bucket_counts = [0] * nslots
        self.exemplars = [None] * nslots
        self.window = collections.deque(maxlen=int(window_maxlen))


class _BoundHistogram:
    """``hist.labels(...)`` binding: observe() lands on BOTH the parent
    aggregate and the labeled child, under one lock acquisition. Bind
    once (e.g. at request admission) and the hot loop pays exactly the
    unlabeled observe() cost — no per-sample label-dict allocation."""

    __slots__ = ("_hist", "_key")

    def __init__(self, hist, key):
        self._hist = hist
        self._key = key

    def observe(self, v, trace_id=None):
        self._hist.observe(v, trace_id=trace_id, labels_key=self._key)


class Histogram(_Metric):
    """Sample distribution with bounded memory.

    Two views, deliberately different windows:

    - ``count`` / ``sum`` / Prometheus bucket counts are EXACT running
      totals over every observation ever made (what rate() and mean
      dashboards need);
    - percentiles (``percentile``, ``snapshot()['p50']``...) are
      computed over a SLIDING WINDOW of the most recent ``maxlen``
      samples (what a latency dashboard wants, and the only way to keep
      a long-running server's memory bounded).

    ``snapshot()['mean']`` is therefore ``sum/count`` over ALL
    observations while p50/p90/p99/min/max describe only the window;
    ``snapshot()['window_count']`` says how many samples the window
    currently holds so dashboards can tell the two populations apart.

    Label support mirrors :class:`Counter`: ``labels(**labels)`` returns
    a bound child whose ``observe`` updates the parent aggregate AND the
    child's own cumulative count/sum/buckets (one lock acquisition), so
    the unlabeled totals never need a sum over children at read time and
    a mixed family stays double-count-free in the exposition (children +
    blank-label remainder)."""

    metric_type = "histogram"

    def __init__(self, name, help="", unit="s", maxlen=65536,
                 buckets=None, prom_name=None, child_window=4096):
        super().__init__(name, help=help, unit=unit, prom_name=prom_name)
        self._samples = collections.deque(maxlen=int(maxlen))
        self._count = 0
        self._sum = 0.0
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        # per-bucket (non-cumulative) counts; last slot is +Inf overflow
        self._bucket_counts = [0] * (len(self.buckets) + 1)
        # bounded per-bucket exemplar: the LAST trace_id observed into
        # each bucket slot (None until one arrives) — links a latency
        # bucket straight to a representative distributed trace
        self._exemplars = [None] * (len(self.buckets) + 1)
        # labels_key -> _HistogramChild; bounded by label cardinality
        # (slo_class is a small closed set)
        self._children = {}
        self._child_window = int(child_window)

    def labels(self, **labels):
        """A bound child for ``labels`` (the parent itself when empty).
        Resolving allocates; the returned binding's observe() does not —
        resolve once at admission, observe per token."""
        if not labels:
            return self
        key = _labels_key(labels)
        with self._lock:
            if key not in self._children:
                self._children[key] = _HistogramChild(
                    len(self._bucket_counts), self._child_window
                )
        return _BoundHistogram(self, key)

    def observe(self, v, trace_id=None, labels_key=None):
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
            idx = bisect.bisect_left(self.buckets, v)
            self._bucket_counts[idx] += 1
            if trace_id is not None:
                self._exemplars[idx] = {
                    "trace_id": str(trace_id), "value": v,
                }
            if labels_key is not None:
                ch = self._children.get(labels_key)
                if ch is None:
                    ch = self._children[labels_key] = _HistogramChild(
                        len(self._bucket_counts), self._child_window
                    )
                ch.count += 1
                ch.sum += v
                ch.bucket_counts[idx] += 1
                ch.window.append(v)
                if trace_id is not None:
                    ch.exemplars[idx] = {
                        "trace_id": str(trace_id), "value": v,
                    }

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    @property
    def window_count(self):
        return len(self._samples)

    def percentile(self, p):
        """p in [0, 100]; nearest-rank over the sliding window. None
        when empty."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        k = max(0, min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count)] over ALL observations, with
        a final (inf, count) entry — the Prometheus exposition shape."""
        with self._lock:
            counts = list(self._bucket_counts)
        out, acc = [], 0
        for ub, c in zip(self.buckets, counts):
            acc += c
            out.append((ub, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def snapshot(self):
        """Plain-dict readout.

        WINDOW SPLIT (read this before graphing): ``count``/``sum``/
        ``mean`` are exact running totals over every observation;
        ``p50``/``p90``/``p99``/``min``/``max`` describe only the most
        recent ``window_count`` samples. With fewer than ``maxlen``
        total observations the two populations coincide."""
        with self._lock:
            if not self._samples:
                return {"count": self._count, "window_count": 0}
            window = sorted(self._samples)
            count, total = self._count, self._sum

        def pct(p):
            k = max(0, min(len(window) - 1,
                           int(round(p / 100.0 * (len(window) - 1)))))
            return window[k]

        return {
            "count": count,
            "sum": total,
            "mean": total / count,
            "window_count": len(window),
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
            "max": window[-1],
            "min": window[0],
            "unit": self.unit,
        }

    def data(self):
        # ONE lock acquisition for window + totals + bucket counts: a
        # concurrent observe between two reads would otherwise emit an
        # exposition where _count disagrees with the +Inf bucket
        # (Prometheus invariant: count == cumulative +Inf)
        with self._lock:
            window = sorted(self._samples)
            count, total = self._count, self._sum
            counts = list(self._bucket_counts)
            exemplars = [
                None if e is None else dict(e) for e in self._exemplars
            ]
            children = [
                (key, ch.count, ch.sum, list(ch.bucket_counts),
                 [None if e is None else dict(e) for e in ch.exemplars],
                 sorted(ch.window))
                for key, ch in self._children.items()
            ]
        d = {"type": self.metric_type, "count": count,
             "window_count": len(window)}
        if window:
            def pct(p):
                k = max(0, min(len(window) - 1,
                               int(round(p / 100.0 * (len(window) - 1)))))
                return window[k]

            d.update(
                sum=total, mean=total / count,
                p50=pct(50), p90=pct(90), p99=pct(99),
                max=window[-1], min=window[0], unit=self.unit,
            )
        buckets, acc = [], 0
        for i, (ub, c) in enumerate(zip(self.buckets, counts)):
            acc += c
            b = {"le": ub, "count": acc}
            if exemplars[i] is not None:
                b["exemplar"] = exemplars[i]
            buckets.append(b)
        inf_b = {"le": float("inf"), "count": acc + counts[-1]}
        if exemplars[-1] is not None:
            inf_b["exemplar"] = exemplars[-1]
        buckets.append(inf_b)
        d["buckets"] = buckets
        d.setdefault("sum", total)
        if children:
            series = []
            for key, c_count, c_sum, c_counts, c_ex, c_win in sorted(
                children, key=lambda it: it[0]
            ):
                cb, acc2 = [], 0
                for i, (ub, c) in enumerate(zip(self.buckets, c_counts)):
                    acc2 += c
                    b = {"le": ub, "count": acc2}
                    if c_ex[i] is not None:
                        b["exemplar"] = c_ex[i]
                    cb.append(b)
                inf_cb = {"le": float("inf"), "count": acc2 + c_counts[-1]}
                if c_ex[-1] is not None:
                    inf_cb["exemplar"] = c_ex[-1]
                cb.append(inf_cb)
                s = {"labels": dict(key), "count": c_count, "sum": c_sum,
                     "buckets": cb}
                if c_win:
                    def cpct(p):
                        k = max(0, min(len(c_win) - 1,
                                       int(round(p / 100.0
                                                 * (len(c_win) - 1)))))
                        return c_win[k]
                    s.update(p50=cpct(50), p99=cpct(99))
                series.append(s)
            d["series"] = series
        return d


class MetricsRegistry:
    """Name -> instrument map with replace-on-register semantics."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.RLock()

    def register(self, metric, replace=True):
        name = metric.prom_name
        with self._lock:
            old = self._metrics.get(name)
            if old is not None and not replace and old is not metric:
                raise ValueError(f"metric {name!r} already registered")
            self._metrics[name] = metric
        return metric

    def register_all(self, metrics):
        for m in metrics:
            self.register(m)

    def unregister(self, name):
        with self._lock:
            return self._metrics.pop(name, None)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def metrics(self):
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def _get_or_create(self, cls, name, help="", **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} is a {m.metric_type}, not a "
                        f"{cls.metric_type}"
                    )
                return m
            m = cls(name, help=help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", **kw) -> Counter:
        return self._get_or_create(Counter, name, help=help, **kw)

    def gauge(self, name, help="", **kw) -> Gauge:
        return self._get_or_create(Gauge, name, help=help, **kw)

    def histogram(self, name, help="", **kw) -> Histogram:
        return self._get_or_create(Histogram, name, help=help, **kw)

    def snapshot(self):
        """JSON-able view of every registered instrument."""
        out = {}
        for m in self.metrics():
            try:
                d = m.data()
            except Exception:
                continue
            d["help"] = m.help
            if m.unit:
                d["unit"] = m.unit
            out[m.prom_name] = d
        return {"metrics": out}

    def prometheus_text(self):
        from .exporter import prometheus_text

        return prometheus_text(self)


# The process-wide default registry: serving, analysis, profiler, and
# training telemetry all publish here unless handed another registry.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
