"""paddle_tpu.observability — unified telemetry for training + serving.

One registry, every signal: serving metrics (``serving.metrics``
re-bases its Counter/Histogram onto this package), static-analysis
guard fires (``analysis.TraceGuard``), profiler lint events, and
training-step telemetry (``StepMeter`` wired into the compiled train
step and the hapi fit loop) all publish into one process-wide
:class:`MetricsRegistry`. Readouts:

- :func:`prometheus_text` / ``registry.snapshot()`` — Prometheus text
  exposition + JSON, both derivable at any moment;
- :func:`start_metrics_server` — stdlib-only HTTP ``/metrics`` +
  ``/metrics.json`` + ``/flight`` endpoint on a daemon thread;
- :class:`FlightRecorder` — a bounded ring of the last K step records
  that dumps a JSON diagnostic bundle on NaN/uncaught exception (hooks
  into the ``FLAGS_check_nan_inf`` machinery) or on demand;
- :func:`merged_report` — per-host registries tagged with process index
  and merged through the distributed layer into one report.

Everything is host-side Python: observing never touches the device, and
lazy gauge values (device-scalar losses) only materialize on scrape.
"""
from __future__ import annotations

from .exporter import (
    MetricsServer,
    parse_prometheus_text,
    prometheus_text,
    start_metrics_server,
)
from .flight_recorder import (
    FlightRecorder,
    get_flight_recorder,
    set_flight_recorder,
)
from .multihost import merge_snapshots, merged_report, tagged_snapshot
from .registry import (
    COUNT_BUCKETS,
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from .slo import (
    DEFAULT_CLASS,
    BurnRateRule,
    SLOClass,
    SLOMonitor,
    SLORegistry,
    UnknownSLOClassError,
    attainment_report,
    default_burn_rules,
    default_classes,
    get_slo_registry,
    set_slo_registry,
    within_budget,
)
from .step_meter import (
    StepMeter,
    analytic_flops_per_token,
    analytic_param_count,
    batch_geometry,
    configure_training,
    device_memory_stats,
    get_step_meter,
    peak_flops_per_device,
    set_step_meter,
)
from .timeseries import TimeSeriesRing
from .tracing import (
    Span,
    SpanBuffer,
    SpanContext,
    Tracer,
    chrome_trace,
    export_chrome,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    remote_child_span,
    set_process_name,
    set_tracer,
    stitch,
    trace_payload,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "DEFAULT_BUCKETS", "COUNT_BUCKETS",
    "prometheus_text", "parse_prometheus_text", "MetricsServer",
    "start_metrics_server",
    "StepMeter", "get_step_meter", "set_step_meter",
    "configure_training", "analytic_flops_per_token",
    "analytic_param_count", "peak_flops_per_device",
    "device_memory_stats", "batch_geometry",
    "FlightRecorder", "get_flight_recorder", "set_flight_recorder",
    "tagged_snapshot", "merge_snapshots", "merged_report",
    "TimeSeriesRing",
    "SLOClass", "SLORegistry", "SLOMonitor", "BurnRateRule",
    "UnknownSLOClassError", "DEFAULT_CLASS",
    "get_slo_registry", "set_slo_registry", "default_classes",
    "default_burn_rules", "attainment_report", "within_budget",
    "Span", "SpanBuffer", "SpanContext", "Tracer",
    "get_tracer", "set_tracer", "set_process_name",
    "parse_traceparent", "format_traceparent", "remote_child_span",
    "stitch", "chrome_trace", "export_chrome", "trace_payload",
]
