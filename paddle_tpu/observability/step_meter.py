"""Training-step telemetry: step time, throughput, MFU, memory gauges.

A :class:`StepMeter` is the training-side counterpart of
``serving.ServingMetrics``: one instrument set publishing into the
process registry. ``jit.trainer.CompiledTrainStep`` and the hapi eager
path call :meth:`StepMeter.observe_step` once per optimizer step with
the host-measured wall time and batch geometry; everything derived —
tokens/sec, examples/sec, the analytic-FLOPs MFU estimate — is computed
on the host from those numbers. The loss (and grad norm, when a caller
has one) are stored as LAZY gauge values: the device scalar is kept as
a reference and only fetched when a scrape materializes it, so metering
never adds a device round trip to the hot loop (the same rule hapi's
lazy logs follow).

MFU uses the standard analytic transformer accounting
(:func:`analytic_flops_per_token` — 2N matmul FLOPs per token forward,
3x for forward+backward, plus the attention ``4*s*h*L`` term) against a
per-device peak from the device kind (override with ``peak_flops=`` or
``PADDLE_TPU_PEAK_FLOPS``). On CPU CI there is no meaningful peak, so
MFU only reports when a peak is known or supplied.

Device-memory gauges sample ``device.memory_stats()`` where the backend
provides it (TPU/GPU) and always publish an aggregate of
``jax.live_arrays()`` bytes (works everywhere, including the CPU CI);
sampling is throttled to every ``memory_every`` steps because
``live_arrays`` walks every live buffer.
"""
from __future__ import annotations

import os
import threading
import time

from .registry import (
    TOKEN_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    get_registry,
)

# bf16 peak FLOPs per chip by device-kind substring (first match wins).
# Sources: public TPU/GPU spec sheets; override via peak_flops= or the
# PADDLE_TPU_PEAK_FLOPS env var when the table is wrong for your part.
PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("h100", 989e12),
    ("a100", 312e12),
)


def peak_flops_per_device(device=None):
    """Per-device peak FLOPs: env override, else device-kind table,
    else None (unknown part / CPU)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    try:
        import jax

        device = device or jax.devices()[0]
    except Exception:
        return None
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, peak in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return None


def analytic_param_count(config):
    """Parameter count from a Llama-family config (duck-typed on the
    fields ``LlamaConfig`` carries; GQA-aware)."""
    h = int(config.hidden_size)
    L = int(config.num_hidden_layers)
    inter = int(getattr(config, "intermediate_size", 4 * h))
    vocab = int(getattr(config, "vocab_size", 0))
    nh = int(getattr(config, "num_attention_heads", 1))
    kvh = int(getattr(config, "num_key_value_heads", None) or nh)
    d = h // max(nh, 1)
    attn = h * (nh * d) + 2 * h * (kvh * d) + (nh * d) * h
    mlp = 3 * h * inter  # gate + up + down (SwiGLU)
    norms = 2 * h
    per_layer = attn + mlp + norms
    embed = vocab * h
    head = 0 if getattr(config, "tie_word_embeddings", False) else vocab * h
    return L * per_layer + embed + head + h  # final norm


def analytic_flops_per_token(config, seq_len=None, include_backward=True):
    """Analytic training FLOPs per token (PaLM-style accounting):
    ``2 * N_matmul`` forward per token plus the attention score/value
    term ``4 * s * h * L``; backward ~2x forward, so training = 3x.
    Embedding lookups are excluded (gathers, not matmuls); the LM head
    matmul is included."""
    h = int(config.hidden_size)
    L = int(config.num_hidden_layers)
    vocab = int(getattr(config, "vocab_size", 0))
    n_matmul = analytic_param_count(config) - vocab * h  # drop embed gather
    if getattr(config, "tie_word_embeddings", False):
        # tied configs carry no separate head PARAMETER, but the shared
        # matrix still executes as the LM-head matmul every token
        n_matmul += vocab * h
    fwd = 2 * n_matmul
    if seq_len:
        fwd += 4 * int(seq_len) * h * L
    return fwd * (3 if include_backward else 1)


def device_memory_stats():
    """Host-side memory readout: per-device backend stats when the
    platform exposes them, plus an aggregate over ``jax.live_arrays()``
    that works on every backend (the CPU CI included)."""
    import jax

    out = {"devices": [], "live_array_bytes": 0, "live_array_count": 0}
    try:
        total, n = 0, 0
        for a in jax.live_arrays():
            total += int(getattr(a, "nbytes", 0) or 0)
            n += 1
        out["live_array_bytes"] = total
        out["live_array_count"] = n
    except Exception:
        pass
    try:
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            out["devices"].append({
                "device": f"{d.platform}:{d.id}",
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", 0)
                ),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            })
    except Exception:
        pass
    return out


def batch_geometry(arrays):
    """(examples, tokens) from a step's input arrays: examples = leading
    dim of the first array; tokens counted only for an integer-dtype
    [B, S] input (token ids) — image/audio batches report 0 tokens."""
    import numpy as np

    for a in arrays:
        shape = getattr(a, "shape", None)
        if not shape:
            continue
        examples = int(shape[0])
        tokens = 0
        dt = getattr(a, "dtype", None)
        if len(shape) == 2 and dt is not None and \
                np.issubdtype(np.dtype(dt), np.integer):
            tokens = int(shape[0]) * int(shape[1])
        return examples, tokens
    return 0, 0


class StepMeter:
    """Per-step training telemetry publishing into the registry.

    Construct with a model/config (or explicit ``flops_per_token``) to
    enable the MFU estimate; without one, MFU stays unreported rather
    than wrong. All instruments register with replace semantics under
    ``paddle_training_*`` / ``paddle_device_*`` names.
    """

    def __init__(self, registry=None, *, recorder=None, model=None,
                 config=None, flops_per_token=None, peak_flops=None,
                 seq_len=None, memory_every=10,
                 namespace="paddle_training"):
        reg = registry or get_registry()
        self.registry = reg
        self._recorder = recorder
        self._lock = threading.Lock()
        self._memory_every = max(1, int(memory_every))
        ns = namespace
        self.step_time = Histogram(
            "step_time", unit="s", prom_name=f"{ns}_step_time_seconds",
            help="wall time of one optimizer step (host-measured)",
        )
        self.compile_time = Histogram(
            "compile_time", unit="s", buckets=(
                0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0,
            ),
            prom_name=f"{ns}_compile_time_seconds",
            help="wall time of warmup steps that included trace+XLA "
                 "compile — kept OUT of step_time so the running "
                 "mean/throughput stay honest",
        )
        self.steps = Counter(
            "steps", prom_name=f"{ns}_steps_total",
            help="optimizer steps taken",
        )
        self.examples = Counter(
            "examples", prom_name=f"{ns}_examples_total",
            help="training examples consumed",
        )
        self.tokens = Counter(
            "tokens", prom_name=f"{ns}_tokens_total",
            help="training tokens consumed (integer [B,S] inputs only)",
        )
        self.tokens_per_second = Gauge(
            "tokens_per_second", prom_name=f"{ns}_tokens_per_second",
            help="throughput of the most recent step",
        )
        self.examples_per_second = Gauge(
            "examples_per_second", prom_name=f"{ns}_examples_per_second",
            help="throughput of the most recent step",
        )
        self.mfu = Gauge(
            "mfu", prom_name=f"{ns}_mfu",
            help="model FLOPs utilization (analytic estimate, 0..1)",
        )
        self.loss = Gauge(
            "loss", prom_name=f"{ns}_loss",
            help="most recent step loss (lazy: fetched on scrape)",
        )
        self.grad_norm = Gauge(
            "grad_norm", prom_name=f"{ns}_grad_norm",
            help="most recent global gradient norm (when available)",
        )
        self.batch_tokens = Histogram(
            "batch_tokens", unit="tokens", buckets=TOKEN_BUCKETS,
            prom_name=f"{ns}_batch_tokens",
            help="tokens per step",
        )
        self.run_breaks = Counter(
            "run_breaks", prom_name=f"{ns}_run_breaks_total",
            help="dispatch gaps past MAX_STEP_GAP_S, by cause: "
                 "checkpoint_stall (writer backpressure / emergency "
                 "save reported via note_blocked), watchdog_fire (a "
                 "train watchdog flagged the step wedged), unknown "
                 "(eval phase, operator pause, or a genuine hang "
                 "nothing instrumented)",
        )
        self.fp8_bytes_saved = Gauge(
            "amp_fp8_matmul_bytes_saved", unit="bytes",
            prom_name=f"{ns}_amp_fp8_matmul_bytes_saved",
            help="analytic HBM bytes per step the AMP O3 fp8 matmul "
                 "routing avoids moving (weight operands at 1 byte "
                 "instead of their stored width); 0 when O3 is off",
        )
        self.device_bytes_in_use = Gauge(
            "device_bytes_in_use", unit="bytes",
            prom_name="paddle_device_bytes_in_use",
            help="device memory in use (backend stats; 'aggregate' = "
                 "sum of live jax arrays, all backends)",
        )
        self.device_peak_bytes = Gauge(
            "device_peak_bytes_in_use", unit="bytes",
            prom_name="paddle_device_peak_bytes_in_use",
            help="peak device memory (backend stats where available)",
        )
        self.device_live_arrays = Gauge(
            "device_live_arrays",
            prom_name="paddle_device_live_arrays",
            help="count of live jax arrays in the process",
        )
        reg.register_all([
            self.step_time, self.compile_time, self.steps,
            self.examples, self.tokens,
            self.tokens_per_second, self.examples_per_second, self.mfu,
            self.loss, self.grad_norm, self.batch_tokens,
            self.run_breaks, self.fp8_bytes_saved,
            self.device_bytes_in_use, self.device_peak_bytes,
            self.device_live_arrays,
        ])
        self._flops_per_token = flops_per_token
        self._seq_len = seq_len
        self._peak_flops = peak_flops
        self._peak_total = None
        self._mem_high_water = 0
        self._last_step_t = None
        self._blocked_pending = 0.0
        self._wedge_pending = False
        self._blocked_listeners = []
        cfg = getattr(model, "config", None) or config
        if self._flops_per_token is None and cfg is not None and \
                hasattr(cfg, "hidden_size"):
            self._flops_per_token = analytic_flops_per_token(
                cfg, seq_len=seq_len
            )

    # ------------------------------------------------------------- config
    def auto_configure(self, network):
        """Derive flops_per_token from a network's config once (no-op
        when already configured or the network has no model config)."""
        if self._flops_per_token is not None:
            return
        cfg = getattr(network, "config", None)
        if cfg is not None and hasattr(cfg, "hidden_size") and \
                hasattr(cfg, "num_hidden_layers"):
            self._flops_per_token = analytic_flops_per_token(cfg)

    def _peak(self):
        if self._peak_total is None:
            per_dev = self._peak_flops
            if per_dev is None:
                per_dev = peak_flops_per_device()
            if per_dev is None:
                self._peak_total = 0.0
            else:
                try:
                    import jax

                    n = max(1, jax.local_device_count())
                except Exception:
                    n = 1
                self._peak_total = float(per_dev) * n
        return self._peak_total

    @property
    def recorder(self):
        """Explicit recorder if one was injected, else whatever the
        CURRENT process default is — resolved per use, never cached, so
        a later ``set_flight_recorder()`` starts receiving records
        immediately instead of feeding a stale black box."""
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import get_flight_recorder

        return get_flight_recorder()

    # -------------------------------------------------------------- steps
    # idle gaps beyond this are a run break (eval phase, user pause),
    # not a slow step — fall back to the caller's host measurement
    MAX_STEP_GAP_S = 60.0

    def note_fp8_bytes_saved(self, n):
        """AMP O3 reports the analytic per-step weight-HBM delta of the
        fp8 matmul routing here (a static trace-time number — no device
        sync)."""
        self.fp8_bytes_saved.set(float(n))

    def note_blocked(self, seconds):
        """Report a train-loop stall that is NOT step work — checkpoint
        writer backpressure, an emergency synchronous save. The stall is
        subtracted from the next dispatch-to-dispatch interval so
        step_time / tokens-per-sec / MFU are not silently deflated by
        save stalls (the caller publishes the stall itself, e.g. into
        ``paddle_ckpt_blocked_seconds``). Attached blocked-listeners
        (the training watchdog's wedge detector) see the same stall so
        they can exclude it from their own gap accounting."""
        with self._lock:
            self._blocked_pending += float(seconds)
            listeners = list(self._blocked_listeners)
        for fn in listeners:
            try:
                fn(seconds)
            except Exception:
                pass

    def add_blocked_listener(self, fn):
        """Forward every ``note_blocked`` stall to ``fn(seconds)`` too
        (the train watchdog registers here, so checkpoint-blocked time
        never reads as a wedged step). Returns an ``undo()``."""
        with self._lock:
            self._blocked_listeners.append(fn)

        def undo():
            with self._lock:
                if fn in self._blocked_listeners:
                    self._blocked_listeners.remove(fn)

        return undo

    def note_wedged(self):
        """A watchdog flagged the CURRENT gap as a wedged step: the
        next run break is attributed to ``watchdog_fire`` instead of
        ``unknown`` in ``paddle_training_run_breaks_total``."""
        with self._lock:
            self._wedge_pending = True

    def observe_step(self, step_time, *, examples=0, tokens=0, loss=None,
                     grad_norm=None, warmup=False):
        """Record one optimizer step. ``loss``/``grad_norm`` may be
        device scalars — they are held as lazy gauge values and only
        fetched when a scrape or crash dump materializes them.

        ``step_time`` is the caller's host-side measurement — on an
        accelerator that is DISPATCH time (jax returns device refs
        before the step executes), which can be far below the true step
        wall time. From the second step on, the meter therefore uses
        the dispatch-to-dispatch interval instead: under steady-state
        training the dispatch rate is throttled to the device step rate
        (jax bounds in-flight computations), so the interval converges
        to true wall-per-step — including input-pipeline time, which is
        what tokens/sec and MFU should honestly reflect. Gaps longer
        than ``MAX_STEP_GAP_S`` are treated as a run break and fall
        back to the caller's measurement.

        ``warmup=True`` marks a step whose wall time included trace+XLA
        compile (the trainer's first call per program): its time lands
        in the ``compile_time`` histogram and the throughput/MFU gauges
        are left alone, so one compile never poisons ``step_time``'s
        exact running sum/mean."""
        step_time = float(step_time)
        now = time.perf_counter()
        with self._lock:
            last, self._last_step_t = self._last_step_t, now
            blocked, self._blocked_pending = self._blocked_pending, 0.0
            wedged, self._wedge_pending = self._wedge_pending, False
        broke = False
        if not warmup and last is not None:
            # checkpoint (and similar) stalls are excluded: they are
            # real wall time but not step work, and would otherwise
            # deflate throughput between checkpoints
            interval = now - last - blocked
            if step_time <= interval <= self.MAX_STEP_GAP_S:
                step_time = interval
            elif interval > self.MAX_STEP_GAP_S:
                # run break: the dispatch-only host dt is wrong-LOW on
                # accelerators — publishing it would spike the
                # throughput/MFU gauges and pollute the histogram's
                # running mean, so this step only counts volume.
                # Attribution makes the exposition actionable: a stall
                # note_blocked reported is a checkpoint stall, a
                # watchdog flag is a wedged step, anything else is an
                # eval/pause/genuine hang.
                broke = True
                if wedged:
                    reason = "watchdog_fire"
                elif blocked > 0:
                    reason = "checkpoint_stall"
                else:
                    reason = "unknown"
                self.run_breaks.inc(reason=reason)
        self.steps.inc()
        if warmup:
            self.compile_time.observe(step_time)
        elif not broke:
            self.step_time.observe(step_time)
        mfu = None
        if examples:
            self.examples.inc(int(examples))
        if tokens:
            self.tokens.inc(int(tokens))
            self.batch_tokens.observe(tokens)
        if step_time > 0 and not warmup and not broke:
            if examples:
                self.examples_per_second.set(examples / step_time)
            if tokens:
                self.tokens_per_second.set(tokens / step_time)
                peak = self._peak()
                if peak and self._flops_per_token:
                    mfu = (tokens * self._flops_per_token / step_time) \
                        / peak
                    self.mfu.set(mfu)
        if loss is not None:
            self.loss.set(loss)  # lazy: materialized on scrape
        if grad_norm is not None:
            self.grad_norm.set(grad_norm)
        n = self.steps.value
        mem = None
        if n == 1 or n % self._memory_every == 0:
            mem = self.sample_memory()
        rec = {
            "step": n,
            "time": time.time(),
            "warmup": bool(warmup),
            "step_time_s": step_time,
            "examples": int(examples),
            "tokens": int(tokens),
            "tokens_per_s": (tokens / step_time)
            if (tokens and step_time > 0) else None,
            "mfu": mfu,
            "loss": loss,
            "grad_norm": grad_norm,
            "bytes_in_use": mem,
            "mem_high_water": self._mem_high_water,
        }
        try:
            self.recorder.record_step(rec)
        except Exception:
            pass
        return rec

    # ------------------------------------------------------------- memory
    def sample_memory(self):
        """Publish device-memory gauges; returns the aggregate byte
        count used for the flight recorder's high-water mark."""
        try:
            stats = device_memory_stats()
        except Exception:
            return None
        agg = stats["live_array_bytes"]
        self.device_bytes_in_use.set(agg, device="aggregate")
        self.device_live_arrays.set(stats["live_array_count"])
        for d in stats["devices"]:
            self.device_bytes_in_use.set(
                d["bytes_in_use"], device=d["device"]
            )
            self.device_peak_bytes.set(
                d["peak_bytes_in_use"], device=d["device"]
            )
            agg = max(agg, d["bytes_in_use"])
        with self._lock:
            if agg > self._mem_high_water:
                self._mem_high_water = agg
        return agg


# ------------------------------------------------------- process default
_DEFAULT = [None]
_DEFAULT_LOCK = threading.Lock()


def get_step_meter() -> StepMeter:
    """The process-default StepMeter (created lazily; the compiled
    trainer and hapi publish through it unless given another)."""
    with _DEFAULT_LOCK:
        if _DEFAULT[0] is None:
            _DEFAULT[0] = StepMeter()
        return _DEFAULT[0]


def set_step_meter(meter):
    """Install ``meter`` as the process default (pass a configured one
    to enable MFU); returns the previous default."""
    with _DEFAULT_LOCK:
        prev, _DEFAULT[0] = _DEFAULT[0], meter
    return prev


def configure_training(**kw):
    """Build + install a configured process-default StepMeter
    (``model=``/``config=``/``flops_per_token=``/``peak_flops=``...)."""
    meter = StepMeter(**kw)
    set_step_meter(meter)
    return meter
