"""Distributed request tracing — stdlib-only spans stitched across the fleet.

A fleet request crosses three processes (router -> replica engine ->
prefill worker; the serving tier of PRs 10-13) and per-process
aggregate histograms cannot say WHICH hop ate a p99 spike. This module
is the missing substrate: per-request spans with W3C-style
``traceparent`` context propagation over the router->replica HTTP hop
and the KV-transfer frame protocol, collected in bounded per-process
buffers and stitched by ``trace_id`` into one cross-process timeline.

Design points (all stdlib; no OTLP wire format — see the COVERAGE
known-gaps note):

- :class:`Span` — W3C-sized ids (``trace_id`` 16 bytes, ``span_id`` 8
  bytes), a wall-clock ``start`` plus a ``perf_counter`` delta for the
  end so durations stay monotonic-accurate even if the wall clock
  steps, attributes, and a BOUNDED per-span event ring: a 500-step
  decode is ONE span carrying O(ring) step events, never 500 spans.
- :class:`Tracer` — head-based sampling decided ONCE at the trace root
  (``PADDLE_TPU_TRACE_SAMPLE``: ``0`` = tracing off, ``1`` = keep all,
  the default; ``N`` = keep 1-in-N, the bench setting). A sampled-out
  request carries ``None`` context and every downstream
  instrumentation site allocates NOTHING — the decode hot path is
  pinned span-free when sampled out.
- :class:`SpanBuffer` — thread-safe bounded store of FINISHED spans
  grouped by trace (oldest trace evicted whole); the backing store of
  the ``/trace`` endpoints.
- Stitching — each process reports wall-clock spans; :func:`stitch`
  maps a child process onto its parent's clock with the NTP pair
  formula over the HTTP/KV request-response timestamps (client span =
  t0/t3, server span = t1/t2) and records the applied
  ``clock_offset_s`` ON the shifted spans: the estimate is honest,
  never hidden.
- :func:`chrome_trace` — profiler-compatible chrome JSON (``"ph":
  "X"`` complete events, microsecond ts/dur) that
  ``paddle_tpu.profiler.load_profiler_result`` reads back and Perfetto
  renders with router/replica/worker as separate named process rows.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import re
import threading
import time

TRACEPARENT_HEADER = "traceparent"
SAMPLE_ENV = "PADDLE_TPU_TRACE_SAMPLE"
PROCESS_ENV = "PADDLE_TPU_TRACE_PROCESS"
DEFAULT_EVENT_RING = 256

_TP_RE = re.compile(
    r"^00-(?P<trace_id>[0-9a-f]{32})-(?P<span_id>[0-9a-f]{16})"
    r"-(?P<flags>[0-9a-f]{2})$"
)


def _rand_hex(nbytes):
    return os.urandom(nbytes).hex()


class SpanContext:
    """A parsed ``traceparent``: just enough to parent a remote child."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id, span_id, sampled=True):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.sampled = bool(sampled)

    def traceparent(self):
        return format_traceparent(self)

    def __repr__(self):
        return (f"SpanContext({self.trace_id[:8]}.., {self.span_id}, "
                f"sampled={self.sampled})")


def parse_traceparent(header):
    """Parse a W3C-style ``traceparent``; ``None`` for absent or
    malformed headers (propagation is best-effort — a bad header means
    "start fresh", never an error on the serving path)."""
    if not header or not isinstance(header, str):
        return None
    m = _TP_RE.match(header.strip().lower())
    if m is None:
        return None
    return SpanContext(
        m.group("trace_id"), m.group("span_id"),
        sampled=bool(int(m.group("flags"), 16) & 1),
    )


def format_traceparent(span_or_ctx, sampled=True):
    """``00-<trace_id>-<span_id>-<flags>`` for a Span or SpanContext."""
    flags = "01" if sampled else "00"
    return (f"00-{span_or_ctx.trace_id}-{span_or_ctx.span_id}-{flags}")


class Span:
    """One timed unit of work inside one process.

    ``start``/``end`` are wall-clock seconds (``time.time`` epoch) so
    spans from different processes land on a common axis before any
    offset correction; the END is derived from a ``perf_counter``
    delta, so a span's DURATION is monotonic-accurate even when the
    wall clock steps mid-span. ``events`` is a bounded ring
    (``maxlen=event_ring``) — high-frequency per-step marks coexist
    with the O(1)-spans-per-request discipline."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "process",
                 "start", "end", "attrs", "events", "_mono0", "_tracer")

    def __init__(self, name, trace_id, span_id, parent_id=None,
                 process="", tracer=None, start=None,
                 event_ring=DEFAULT_EVENT_RING):
        self.name = str(name)
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.parent_id = None if parent_id is None else str(parent_id)
        self.process = str(process)
        now = time.time()
        self.start = now if start is None else float(start)
        # anchored so (perf_now - _mono0) measures from self.start even
        # for retroactive spans whose start predates construction
        self._mono0 = time.perf_counter() - (now - self.start)
        self.end = None
        self.attrs = {}
        self.events = collections.deque(maxlen=int(event_ring))
        self._tracer = tracer

    # ------------------------------------------------------------ content
    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def event(self, name, **fields):
        """Append one bounded-ring event (e.g. a decode step mark)."""
        ev = {"name": str(name),
              "t": self.start + (time.perf_counter() - self._mono0)}
        ev.update(fields)
        self.events.append(ev)
        return self

    # ---------------------------------------------------------- lifecycle
    @property
    def finished(self):
        return self.end is not None

    @property
    def duration(self):
        return None if self.end is None else self.end - self.start

    def traceparent(self):
        return format_traceparent(self)

    def finish(self, end=None, **attrs):
        """Idempotent close; pushes the span into its tracer's buffer."""
        if self.end is not None:
            return self
        self.attrs.update(attrs)
        self.end = (self.start + (time.perf_counter() - self._mono0)
                    if end is None else float(end))
        if self._tracer is not None:
            self._tracer._finished(self)
        return self

    # -------------------------------------------------------------- wire
    def to_dict(self):
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "process": self.process,
            "start": self.start,
            "end": self.end,
            "attrs": dict(self.attrs),
            "events": [dict(e) for e in self.events],
        }

    def __repr__(self):
        state = "open" if self.end is None else f"{self.duration:.6f}s"
        return (f"Span({self.name!r}, {self.process}, "
                f"{self.trace_id[:8]}.., {state})")


class SpanBuffer:
    """Thread-safe bounded store of finished spans, grouped by trace.

    Eviction is trace-granular (oldest trace dropped whole — a
    half-evicted trace would stitch into nonsense), bounded both by
    trace count and total span count. Stores plain dicts so spans
    shipped from another process (the KV-frame return path) ingest
    through the same :meth:`add`."""

    def __init__(self, max_spans=4096, max_traces=256):
        self.max_spans = int(max_spans)
        self.max_traces = int(max_traces)
        self._traces = collections.OrderedDict()  # trace_id -> [dict]
        self._count = 0
        self._lock = threading.Lock()

    def add(self, span_dict):
        tid = str(span_dict.get("trace_id"))
        with self._lock:
            lst = self._traces.get(tid)
            if lst is None:
                self._traces[tid] = lst = []
            else:
                self._traces.move_to_end(tid)
            lst.append(dict(span_dict))
            self._count += 1
            while (len(self._traces) > self.max_traces
                   or self._count > self.max_spans):
                if len(self._traces) == 1:
                    # single oversized trace: trim its oldest spans
                    drop = self._count - self.max_spans
                    if drop <= 0:
                        break
                    del lst[:drop]
                    self._count -= drop
                    break
                _, dropped = self._traces.popitem(last=False)
                self._count -= len(dropped)

    def __len__(self):
        with self._lock:
            return self._count

    def trace_ids(self):
        with self._lock:
            return list(self._traces)

    def get(self, trace_id):
        with self._lock:
            return [dict(s) for s in self._traces.get(str(trace_id), ())]

    def traces(self, limit=None):
        """Recent traces, most recently touched FIRST."""
        with self._lock:
            items = [(t, [dict(s) for s in sp])
                     for t, sp in self._traces.items()]
        items.reverse()
        if limit is not None:
            items = items[: int(limit)]
        return [{"trace_id": t, "spans": sp} for t, sp in items]

    def spans(self):
        with self._lock:
            return [dict(s) for sp in self._traces.values() for s in sp]

    def clear(self):
        with self._lock:
            self._traces.clear()
            self._count = 0


class Tracer:
    """Span factory with head-based sampling + in-flight registry.

    The sampling decision happens exactly once per trace, at
    :meth:`start_trace` — everything downstream keys off whether it
    holds a parent span (``None`` = sampled out = allocate nothing).
    ``sample`` resolves from ``PADDLE_TPU_TRACE_SAMPLE`` at each root
    (0 = off, 1 = keep all, N = 1-in-N) unless pinned by the
    constructor. Unfinished spans are tracked (bounded) so a
    flight-recorder bundle can name the requests in flight."""

    def __init__(self, process=None, buffer=None, sample=None,
                 event_ring=DEFAULT_EVENT_RING, max_active=4096):
        self.process = (process or os.environ.get(PROCESS_ENV)
                        or f"pid{os.getpid()}")
        self.buffer = buffer if buffer is not None else SpanBuffer()
        self._sample = sample
        self._heads = itertools.count()
        self.event_ring = int(event_ring)
        self.spans_started = 0
        self._active = collections.OrderedDict()
        self._max_active = int(max_active)
        self._lock = threading.Lock()

    # ----------------------------------------------------------- sampling
    @property
    def sample(self):
        if self._sample is not None:
            return int(self._sample)
        try:
            return int(os.environ.get(SAMPLE_ENV, "1"))
        except ValueError:
            return 1

    def _head_sampled(self):
        n = self.sample
        if n <= 0:
            return False
        if n == 1:
            return True
        return next(self._heads) % n == 0

    # ----------------------------------------------------------- creation
    def _make(self, name, trace_id, parent_id, attrs, start=None,
              process=None):
        sp = Span(name, trace_id, _rand_hex(8), parent_id=parent_id,
                  process=self.process if process is None else process,
                  tracer=self, start=start, event_ring=self.event_ring)
        if attrs:
            sp.attrs.update(attrs)
        with self._lock:
            self.spans_started += 1
            self._active[sp.span_id] = sp
            while len(self._active) > self._max_active:
                self._active.popitem(last=False)
        return sp

    def start_trace(self, name, process=None, **attrs):
        """New root span — THE head-sampling point. ``None`` when this
        trace is sampled out; callers propagate that ``None`` and no
        further tracing work happens for the request."""
        if not self._head_sampled():
            return None
        return self._make(name, _rand_hex(16), None, attrs,
                          process=process)

    def start_span(self, name, parent, process=None, **attrs):
        """Child span under ``parent`` (a Span, SpanContext, or raw
        traceparent string). ``None`` parent — or an unsampled /
        malformed remote context — yields ``None``: sampled-out stays
        allocation-free all the way down."""
        if parent is None:
            return None
        if isinstance(parent, str):
            parent = parse_traceparent(parent)
            if parent is None:
                return None
        if isinstance(parent, SpanContext) and not parent.sampled:
            return None
        return self._make(name, parent.trace_id, parent.span_id, attrs,
                          process=process)

    def record_span(self, name, parent, duration, end=None, **attrs):
        """Already-finished retroactive span: ends now (or at ``end``),
        started ``duration`` earlier — how the engine renders a
        scheduler-measured queue wait as a span without having traced
        through the queue. ``None`` parent => ``None``."""
        if parent is None:
            return None
        t1 = time.time() if end is None else float(end)
        sp = self.start_span(name, parent, **attrs)
        if sp is None:
            return None
        sp.start = t1 - float(duration)
        sp._mono0 = time.perf_counter() - (time.time() - sp.start)
        return sp.finish(end=t1)

    def record_trace(self, name, duration, end=None, **attrs):
        """Retroactive ROOT span (head-sampled): e.g. the engine's
        reload admission-pause, which is request-independent."""
        if not self._head_sampled():
            return None
        t1 = time.time() if end is None else float(end)
        sp = self._make(name, _rand_hex(16), None, attrs)
        sp.start = t1 - float(duration)
        sp._mono0 = time.perf_counter() - (time.time() - sp.start)
        return sp.finish(end=t1)

    # ------------------------------------------------------------ plumbing
    def _finished(self, span):
        with self._lock:
            self._active.pop(span.span_id, None)
        self.buffer.add(span.to_dict())

    def active_spans(self):
        with self._lock:
            act = list(self._active.values())
        return [s.to_dict() for s in act]

    def active_trace_ids(self):
        with self._lock:
            return sorted({s.trace_id for s in self._active.values()})


def remote_child_span(name, ctx, process, event_ring=DEFAULT_EVENT_RING):
    """A span for remote-parented work whose record travels back to the
    caller IN the response (the KV-frame pattern: the prefill worker
    ships its span dict in the ``prefilled`` header and the CLIENT's
    buffer records it) — deliberately tracer-less so an in-process
    worker doesn't double-record into the shared buffer."""
    if isinstance(ctx, str):
        ctx = parse_traceparent(ctx)
    if ctx is None or not getattr(ctx, "sampled", True):
        return None
    return Span(name, ctx.trace_id, _rand_hex(8),
                parent_id=ctx.span_id, process=process,
                event_ring=event_ring)


# ------------------------------------------------------- process default
_DEFAULT = [None]
_DEFAULT_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    with _DEFAULT_LOCK:
        if _DEFAULT[0] is None:
            _DEFAULT[0] = Tracer()
        return _DEFAULT[0]


def set_tracer(tracer):
    with _DEFAULT_LOCK:
        prev, _DEFAULT[0] = _DEFAULT[0], tracer
    return prev


def set_process_name(name):
    """Tag this process's spans (launch.py sets the fleet role)."""
    get_tracer().process = str(name)


# ------------------------------------------------------------- stitching
def estimate_offset(client_span, server_span):
    """NTP pair estimate of (server clock - client clock): with the
    client span bracketing the request (t0=start, t3=end) and the
    server span the handling (t1=start, t2=end),
    ``((t1-t0)+(t2-t3))/2`` is the classic symmetric-delay offset.
    Subtract it from server times to land on the client's clock."""
    t0, t3 = float(client_span["start"]), float(client_span["end"])
    t1 = float(server_span["start"])
    t2 = (float(server_span["end"])
          if server_span.get("end") is not None else t1)
    return ((t1 - t0) + (t2 - t3)) / 2.0


def stitch(spans):
    """Cross-process alignment: group span dicts by trace, pick the
    root process (the one holding the parentless span), and chain NTP
    offsets along cross-process parent->child edges (router->replica
    HTTP hop, replica->worker KV hop). Shifted spans carry the applied
    ``clock_offset_s`` attribute — the estimate is explicit, not
    hidden. Returns adjusted COPIES; input is untouched."""
    flat = []
    for s in spans:
        if "spans" in s and "trace_id" in s and "span_id" not in s:
            flat.extend(s["spans"])  # accept /trace-style groups too
        else:
            flat.append(s)
    by_trace = collections.OrderedDict()
    for s in flat:
        by_trace.setdefault(str(s.get("trace_id")), []).append(s)
    out = []
    for group in by_trace.values():
        out.extend(_stitch_one(group))
    return out


def _stitch_one(group):
    by_id = {s["span_id"]: s for s in group}
    edges = {}  # (client_proc, server_proc) -> (client, server)
    for s in group:
        p = by_id.get(s.get("parent_id") or "")
        if (p is not None and p.get("process") != s.get("process")
                and p.get("end") is not None):
            edges.setdefault(
                (p["process"], s["process"]), (p, s)
            )
    root = next(
        (s["process"] for s in group if not s.get("parent_id")),
        group[0]["process"],
    )
    offset = {root: 0.0}
    changed = True
    while changed:
        changed = False
        for (cp, sp), (c, s) in edges.items():
            if cp in offset and sp not in offset:
                offset[sp] = offset[cp] + estimate_offset(c, s)
                changed = True
    out = []
    for s in group:
        d = dict(s)
        d["attrs"] = dict(s.get("attrs") or {})
        off = offset.get(s.get("process"), 0.0)
        if off:
            d["start"] = float(d["start"]) - off
            if d.get("end") is not None:
                d["end"] = float(d["end"]) - off
            d["events"] = [
                dict(e, t=float(e.get("t", 0.0)) - off)
                for e in (s.get("events") or ())
            ]
            d["attrs"]["clock_offset_s"] = off
        out.append(d)
    return out


# ---------------------------------------------------------- chrome export
def chrome_trace(spans, normalize=True):
    """Span dicts -> chrome://tracing JSON dict. Complete events use
    ``"ph": "X"`` with microsecond ``ts``/``dur`` — byte-compatible
    with what ``profiler.export_chrome_tracing`` writes, so
    ``profiler.load_profiler_result`` reads the file back and Perfetto
    opens it directly. Each fleet process gets its own ``pid`` row
    (named via ``process_name`` metadata); traces stack as one ``tid``
    lane per (process, trace). Span events become instant (``"i"``)
    marks — skipped by the loader, visible in Perfetto."""
    flat = stitch(spans) if spans else []
    flat = [s for s in flat if s.get("end") is not None]
    pids, lanes = {}, {}
    for s in flat:
        pids.setdefault(s.get("process") or "?", len(pids) + 1)
        key = (s.get("process") or "?", s.get("trace_id"))
        lanes.setdefault(key, len([
            1 for k in lanes if k[0] == (s.get("process") or "?")
        ]))
    t0 = min((float(s["start"]) for s in flat), default=0.0) \
        if normalize else 0.0
    events = []
    for proc, pid in pids.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": proc},
        })
    for s in flat:
        proc = s.get("process") or "?"
        pid = pids[proc]
        tid = lanes[(proc, s.get("trace_id"))]
        args = dict(s.get("attrs") or {})
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append({
            "name": s.get("name", ""), "cat": "span", "ph": "X",
            "ts": (float(s["start"]) - t0) * 1e6,
            "dur": (float(s["end"]) - float(s["start"])) * 1e6,
            "pid": pid, "tid": tid, "args": args,
        })
        for e in s.get("events") or ():
            ea = {k: v for k, v in e.items() if k not in ("name", "t")}
            ea["trace_id"] = s.get("trace_id")
            events.append({
                "name": e.get("name", "event"), "cat": "span_event",
                "ph": "i", "s": "t",
                "ts": (float(e.get("t", s["start"])) - t0) * 1e6,
                "pid": pid, "tid": tid, "args": ea,
            })
    return {"traceEvents": events}


def export_chrome(path, spans, normalize=True):
    """Write :func:`chrome_trace` JSON to ``path``; returns the path."""
    doc = chrome_trace(spans, normalize=normalize)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def trace_payload(tracer=None, limit=64):
    """The ``/trace`` endpoint body: this process's recent finished
    traces (front-ends serve it via ``httpd.send_json``)."""
    tr = tracer or get_tracer()
    return {
        "process": tr.process,
        "sample": tr.sample,
        "traces": tr.buffer.traces(limit=limit),
    }
