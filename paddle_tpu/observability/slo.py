"""SLO classes, per-class attainment, and multi-window burn-rate alerts.

Requests arrive with an ``slo_class`` (``interactive`` by default) whose
latency budgets — TTFT / ITL / E2E at p99 — live in a small process
registry. The serving engines stamp the class onto the existing latency
histograms as a label (resolved ONCE at admission, so the greedy decode
hot loop pays nothing), and :class:`SLOMonitor` turns those cumulative
labeled buckets into the windowed view the control plane needs:

- attainment: the fraction of a class's requests inside budget over a
  window, computed from bucket DELTAS on a :class:`TimeSeriesRing` (a
  cumulative ratio would never recover from a past incident);
- burn rate: ``(1 - attainment) / (1 - target)`` — 1.0 means the error
  budget burns exactly at the sustainable pace, N means N× too fast.
  Each :class:`BurnRateRule` is evaluated on a fast AND a slow window
  (the classic SRE pairing: the fast window catches a sudden breach in
  seconds, the slow window holds the alert through flapping);
- alert fan-out: firing/clearing lands in the flight-recorder event
  ring, a ``paddle_alerts_active{rule,slo_class}`` gauge, and the
  ``/alerts`` endpoints the frontends and fleet router expose.

No wall-clock is read outside ``SLOMonitor(clock=...)`` — tests drive
every window with a fake timer, the same discipline as ``autotune``.
"""

from __future__ import annotations

import math
import threading
import time

from .flight_recorder import get_flight_recorder
from .registry import get_registry
from .timeseries import TimeSeriesRing

DEFAULT_CLASS = "interactive"

_BUDGET_FIELDS = ("ttft", "itl", "e2e")


class UnknownSLOClassError(ValueError):
    """Raised by :meth:`SLORegistry.validate` for a class no one
    registered — the frontend maps it to a 400 at the wire."""


class SLOClass:
    """One named traffic class with p99 latency budgets (seconds) and an
    attainment target (fraction of requests that must be in budget)."""

    __slots__ = ("name", "ttft_p99_s", "itl_p99_s", "e2e_p99_s", "target")

    def __init__(self, name, *, ttft_p99_s, itl_p99_s, e2e_p99_s,
                 target=0.99):
        self.name = str(name)
        self.ttft_p99_s = float(ttft_p99_s)
        self.itl_p99_s = float(itl_p99_s)
        self.e2e_p99_s = float(e2e_p99_s)
        self.target = float(target)
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"SLO class {name!r}: target must be in (0, 1), "
                f"got {target}"
            )

    def budget(self, metric):
        """Budget in seconds for ``metric`` in {'ttft','itl','e2e'}."""
        if metric not in _BUDGET_FIELDS:
            raise KeyError(f"unknown SLO metric {metric!r}")
        return getattr(self, f"{metric}_p99_s")

    def to_dict(self):
        return {
            "name": self.name,
            "ttft_p99_s": self.ttft_p99_s,
            "itl_p99_s": self.itl_p99_s,
            "e2e_p99_s": self.e2e_p99_s,
            "target": self.target,
        }

    def __repr__(self):
        return (f"SLOClass({self.name!r}, ttft={self.ttft_p99_s}, "
                f"itl={self.itl_p99_s}, e2e={self.e2e_p99_s}, "
                f"target={self.target})")


def default_classes():
    """The stock traffic classes. Budgets are deliberate, not arbitrary:
    interactive chat needs sub-second first token and smooth streaming;
    RAG tolerates a longer prefill (retrieval-sized prompts); batch is
    throughput-only; agent loops sit between — each turn blocks a tool
    chain, but a human is not watching every token."""
    return [
        SLOClass("interactive", ttft_p99_s=0.5, itl_p99_s=0.1,
                 e2e_p99_s=10.0, target=0.99),
        SLOClass("rag", ttft_p99_s=2.0, itl_p99_s=0.2,
                 e2e_p99_s=30.0, target=0.95),
        SLOClass("batch", ttft_p99_s=30.0, itl_p99_s=1.0,
                 e2e_p99_s=600.0, target=0.90),
        SLOClass("agent", ttft_p99_s=1.0, itl_p99_s=0.15,
                 e2e_p99_s=120.0, target=0.95),
    ]


class SLORegistry:
    """Name -> :class:`SLOClass`. Replace-on-add, like the metrics
    registry."""

    def __init__(self, classes=None):
        self._classes = {}
        self._lock = threading.Lock()
        for c in (default_classes() if classes is None else classes):
            self.add(c)

    def add(self, slo_class):
        with self._lock:
            self._classes[slo_class.name] = slo_class
        return slo_class

    def get(self, name):
        with self._lock:
            return self._classes.get(str(name))

    def names(self):
        with self._lock:
            return sorted(self._classes)

    def __contains__(self, name):
        with self._lock:
            return str(name) in self._classes

    def validate(self, name):
        """Resolve a wire-level class name: ``None``/empty defaults to
        ``interactive``; an unknown name raises
        :class:`UnknownSLOClassError` (the frontend's 400)."""
        if name is None or name == "":
            return DEFAULT_CLASS
        name = str(name)
        with self._lock:
            if name not in self._classes:
                known = ", ".join(sorted(self._classes))
                raise UnknownSLOClassError(
                    f"unknown slo_class {name!r} (known: {known})"
                )
        return name

    def table(self):
        with self._lock:
            return [self._classes[k].to_dict()
                    for k in sorted(self._classes)]


_DEFAULT_SLO = [None]
_DEFAULT_SLO_LOCK = threading.Lock()


def get_slo_registry() -> SLORegistry:
    with _DEFAULT_SLO_LOCK:
        if _DEFAULT_SLO[0] is None:
            _DEFAULT_SLO[0] = SLORegistry()
        return _DEFAULT_SLO[0]


def set_slo_registry(registry):
    """Swap the process-default class registry (tests, smoke gates with
    deliberately tight budgets). Returns the previous one."""
    with _DEFAULT_SLO_LOCK:
        prev, _DEFAULT_SLO[0] = _DEFAULT_SLO[0], registry
    return prev


def within_budget(buckets, budget_s):
    """Estimated count of observations ``<= budget_s`` from cumulative
    ``[{"le": ..., "count": ...}]`` (Prometheus shape, +Inf last).

    Linear interpolation inside the bucket the budget falls in — exact
    at bucket boundaries, and monotone in between. Mass in the +Inf
    overflow bucket counts as BREACHING (conservative: we cannot know
    how far past the last finite bound those requests landed)."""
    budget = float(budget_s)
    prev_le, prev_c = 0.0, 0
    for b in buckets:
        le, c = float(b["le"]), int(b["count"])
        if math.isinf(le):
            # past every finite bound: everything beyond prev_c breaches
            return float(prev_c)
        if budget <= le:
            span = le - prev_le
            frac = 1.0 if span <= 0 else (budget - prev_le) / span
            return prev_c + (c - prev_c) * max(0.0, min(1.0, frac))
        prev_le, prev_c = le, c
    return float(prev_c)


def attainment_report(registry=None, slo_registry=None,
                      namespace="paddle_serving"):
    """Cumulative (whole-process) per-class attainment straight off the
    labeled serving histograms — no ring required. The shape
    ``serve_bench`` embeds as its ``slo`` block:

    ``{cls: {"target": t, "ttft": {"budget_s", "total", "within",
    "breaches", "attainment"}, "itl": {...}, "e2e": {...}}}``"""
    registry = registry or get_registry()
    slo_registry = slo_registry or get_slo_registry()
    out = {}
    for metric in _BUDGET_FIELDS:
        hist = registry.get(f"{namespace}_{metric}_seconds")
        if hist is None:
            continue
        try:
            d = hist.data()
        except Exception:
            continue
        for s in d.get("series") or []:
            cls = s.get("labels", {}).get("slo_class")
            if cls is None:
                continue
            sc = slo_registry.get(cls)
            if sc is None:
                continue
            total = int(s.get("count", 0))
            if total <= 0:
                continue
            ok = within_budget(s["buckets"], sc.budget(metric))
            entry = out.setdefault(cls, {"target": sc.target})
            entry[metric] = {
                "budget_s": sc.budget(metric),
                "total": total,
                "within": ok,
                "breaches": max(0, round(total - ok)),
                "attainment": min(1.0, ok / total),
            }
    return out


class BurnRateRule:
    """One declarative multi-window burn-rate rule over a class/metric.

    Burn rate = ``(1 - attainment) / (1 - target)``. The rule yields two
    sub-alerts, ``<name>:fast`` and ``<name>:slow``: the fast window
    with the higher burn threshold pages quickly on a sudden breach; the
    slow window with burn >= 1 catches a sustained simmer and keeps the
    alert from flapping as the fast window rolls off. ``min_requests``
    suppresses verdicts on windows too thin to mean anything (one slow
    request at 3 a.m. is not an incident)."""

    __slots__ = ("name", "slo_class", "metric", "fast_window_s",
                 "slow_window_s", "fast_burn", "slow_burn",
                 "min_requests", "target")

    def __init__(self, name, slo_class, *, metric="ttft",
                 fast_window_s=60.0, slow_window_s=300.0,
                 fast_burn=2.0, slow_burn=1.0, min_requests=3,
                 target=None):
        if metric not in _BUDGET_FIELDS:
            raise KeyError(f"unknown SLO metric {metric!r}")
        self.name = str(name)
        self.slo_class = str(slo_class)
        self.metric = metric
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.min_requests = int(min_requests)
        self.target = None if target is None else float(target)

    def windows(self):
        return (("fast", self.fast_window_s, self.fast_burn),
                ("slow", self.slow_window_s, self.slow_burn))

    def to_dict(self):
        return {
            "name": self.name, "slo_class": self.slo_class,
            "metric": self.metric,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn": self.fast_burn, "slow_burn": self.slow_burn,
            "min_requests": self.min_requests, "target": self.target,
        }


def default_burn_rules(slo_registry=None):
    """One TTFT burn-rate rule per registered class — first-token
    latency is the budget users feel first and the one admission-level
    scheduling can actually move."""
    slo_registry = slo_registry or get_slo_registry()
    return [
        BurnRateRule(f"{name}_ttft", name, metric="ttft")
        for name in slo_registry.names()
    ]


class SLOMonitor:
    """Samples the metrics registry into a :class:`TimeSeriesRing` and
    evaluates burn-rate rules on the windowed deltas.

    Drive it manually with ``sample()`` (tests, deterministic clocks) or
    start the background thread with ``start()``. All alert state
    transitions fan out on the sampling thread: a flight-recorder
    ``note``, the ``paddle_alerts_active`` gauge, and the ``/alerts``
    JSON the frontends serve from :meth:`status`."""

    def __init__(self, registry=None, slo_registry=None, rules=None,
                 interval_s=5.0, capacity=720, clock=time.monotonic,
                 recorder=None, namespace="paddle_serving",
                 gauge_name="paddle_alerts_active"):
        self.registry = registry or get_registry()
        self.slo_registry = slo_registry or get_slo_registry()
        self.rules = list(default_burn_rules(self.slo_registry)
                          if rules is None else rules)
        self.interval_s = float(interval_s)
        self.clock = clock
        self.namespace = namespace
        self.recorder = recorder or get_flight_recorder()
        self.ring = TimeSeriesRing(capacity)
        self.samples_taken = 0
        self._active = {}  # (rule_name, severity) -> alert dict
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._gauge = self.registry.gauge(
            gauge_name,
            help="1 while a burn-rate alert is firing, 0 after it "
                 "clears (labels: rule, slo_class)",
        )
        # newest monitor owns the bundle section (replace-on-register)
        self.recorder.add_section("slo", self._flight_section)

    # ------------------------------------------------------------ sampling
    def _extract(self):
        """One flat sample of every cumulative series the rules need."""
        out = {}
        ns = self.slo_registry
        for metric in _BUDGET_FIELDS:
            hist = self.registry.get(f"{self.namespace}_{metric}_seconds")
            if hist is None:
                continue
            try:
                d = hist.data()
            except Exception:
                continue
            for s in d.get("series") or []:
                cls = s.get("labels", {}).get("slo_class")
                sc = None if cls is None else ns.get(cls)
                if sc is None:
                    continue
                out[f"{metric}.{cls}.total"] = float(s.get("count", 0))
                out[f"{metric}.{cls}.within"] = within_budget(
                    s["buckets"], sc.budget(metric)
                )
        # operational context series the autoscaler will want next to
        # attainment: queue pressure, shed/reject pressure, page misses
        qd = self.registry.get(f"{self.namespace}_queue_depth")
        if qd is not None:
            try:
                out["queue_depth.sum"] = float(qd.sum)
                out["queue_depth.count"] = float(qd.count)
            except Exception:
                pass
        for cname in ("sheds", "rejected"):
            ctr = self.registry.get(f"{self.namespace}_{cname}_total")
            if ctr is None:
                continue
            try:
                d = ctr.data()
            except Exception:
                continue
            out[f"{cname}.total"] = float(d.get("value", 0.0))
            for s in d.get("series") or []:
                for v in s.get("labels", {}).values():
                    out[f"{cname}.{v}"] = float(s.get("value", 0.0))
        return out

    def sample(self, now=None):
        """Take one sample and evaluate every rule. Returns the sample
        dict (handy in tests)."""
        now = self.clock() if now is None else float(now)
        values = self._extract()
        self.ring.append(now, values)
        self.samples_taken += 1
        self._evaluate(now)
        return values

    # ---------------------------------------------------------- attainment
    def attainment(self, slo_class, metric="ttft", window_s=60.0,
                   now=None):
        """Windowed attainment for a class/metric from ring deltas, or
        ``None`` when the window holds no completed requests."""
        total = self.ring.delta(f"{metric}.{slo_class}.total",
                                window_s, now)
        if total <= 0:
            return None
        ok = self.ring.delta(f"{metric}.{slo_class}.within",
                             window_s, now)
        return min(1.0, ok / total)

    def _evaluate(self, now):
        fired, cleared = [], []
        with self._lock:
            for rule in self.rules:
                sc = self.slo_registry.get(rule.slo_class)
                target = rule.target if rule.target is not None else (
                    sc.target if sc is not None else 0.99
                )
                for sev, window_s, burn_thr in rule.windows():
                    total = self.ring.delta(
                        f"{rule.metric}.{rule.slo_class}.total",
                        window_s, now,
                    )
                    att = self.attainment(rule.slo_class, rule.metric,
                                          window_s, now)
                    firing = False
                    burn = None
                    if att is not None and total >= rule.min_requests:
                        burn = (1.0 - att) / max(1e-9, 1.0 - target)
                        firing = burn >= burn_thr
                    key = (rule.name, sev)
                    cur = self._active.get(key)
                    if firing and cur is None:
                        alert = {
                            "rule": f"{rule.name}:{sev}",
                            "slo_class": rule.slo_class,
                            "metric": rule.metric,
                            "severity": sev,
                            "window_s": window_s,
                            "burn": burn,
                            "burn_threshold": burn_thr,
                            "attainment": att,
                            "target": target,
                            "since": now,
                        }
                        self._active[key] = alert
                        fired.append(alert)
                    elif firing and cur is not None:
                        cur.update(burn=burn, attainment=att)
                    elif not firing and cur is not None:
                        cleared.append(self._active.pop(key))
        for alert in fired:
            self._gauge.set(1, rule=alert["rule"],
                            slo_class=alert["slo_class"])
            self.recorder.note("slo_alert", **alert)
        for alert in cleared:
            self._gauge.set(0, rule=alert["rule"],
                            slo_class=alert["slo_class"])
            self.recorder.note("slo_alert_cleared", rule=alert["rule"],
                               slo_class=alert["slo_class"])

    # ------------------------------------------------------------- readout
    def active_alerts(self):
        with self._lock:
            return sorted((dict(a) for a in self._active.values()),
                          key=lambda a: a["rule"])

    def alerts_block(self):
        """The compact block ``/healthz`` embeds (what the fleet router
        scrapes): active alerts plus enough context to aggregate."""
        active = self.active_alerts()
        return {
            "active": active,
            "count": len(active),
            "samples": self.samples_taken,
            "interval_s": self.interval_s,
        }

    def status(self):
        """Full ``/alerts`` payload: active alerts, rule table, class
        table, and current fast/slow attainment per rule."""
        att = {}
        for rule in self.rules:
            e = att.setdefault(rule.slo_class, {})
            for sev, window_s, _ in rule.windows():
                e[f"{rule.metric}_{sev}"] = {
                    "window_s": window_s,
                    "attainment": self.attainment(
                        rule.slo_class, rule.metric, window_s
                    ),
                }
        return {
            "alerts": self.active_alerts(),
            "rules": [r.to_dict() for r in self.rules],
            "classes": self.slo_registry.table(),
            "attainment": att,
            "samples": self.samples_taken,
        }

    def _flight_section(self, k=8):
        return {
            "active_alerts": self.active_alerts(),
            "window_samples": [
                {"t": t, "values": v} for t, v in self.ring.last(k)
            ],
        }

    # ----------------------------------------------------------- lifecycle
    def start(self):
        """Start the background sampling thread (daemon; idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception:  # pragma: no cover - defensive
                    pass  # the monitor must never take the server down

        self._thread = threading.Thread(
            target=_loop, name="slo-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
