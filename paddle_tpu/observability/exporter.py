"""Prometheus text exposition + stdlib-only /metrics HTTP endpoint.

The registry's wire formats:

- :func:`prometheus_text` renders a :class:`~.registry.MetricsRegistry`
  in Prometheus text format 0.0.4 (``# HELP``/``# TYPE`` headers,
  ``_total`` counters, cumulative ``_bucket{le=...}`` histograms).
- :func:`parse_prometheus_text` reads that format back into
  ``{series_name: [(labels, value), ...]}`` — used by the smoke gate to
  assert the exposition is well-formed without a prometheus dependency.
- :class:`MetricsServer` serves ``/metrics`` (text), ``/metrics.json``
  (registry snapshot), ``/flight`` (the flight recorder's current
  bundle), and ``/trace`` (recent finished spans from the process
  tracer) from a daemon thread over ``http.server`` — no third-party
  server; scraping a training job is one stdlib import away.
"""
from __future__ import annotations

import json
import math
import os
import re
import threading

from .registry import get_registry

# OpenMetrics-style exemplars in the text exposition are behind a flag:
# classic Prometheus text-format scrapers reject the suffix, so emitting
# it must be an explicit choice (env or prometheus_text(exemplars=True))
EXEMPLARS_ENV = "PADDLE_TPU_METRICS_EXEMPLARS"


def _exemplars_enabled(flag):
    if flag is not None:
        return bool(flag)
    return os.environ.get(EXEMPLARS_ENV, "").lower() in (
        "1", "true", "yes", "on"
    )


def _fmt_exemplar(ex):
    """OpenMetrics exemplar suffix: `` # {trace_id="..."} <value>``."""
    if not ex:
        return ""
    labels = {
        k: v for k, v in ex.items() if k not in ("value",)
    }
    return (f" # {_fmt_labels(labels) or '{}'}"
            f" {_fmt_value(float(ex.get('value', 0.0)))}")

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_FIX = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize_name(name):
    if _NAME_OK.match(name):
        return name
    name = _NAME_FIX.sub("_", name)
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _escape_label(v):
    # \r must be escaped too: splitlines() (ours and Prometheus's line
    # scanner) would split a label value mid-line otherwise
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n").replace("\r", "\\r")
    )


def _fmt_labels(labels):
    if not labels:
        return ""
    inner = ",".join(
        f'{_LABEL_FIX.sub("_", str(k))}="{_escape_label(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v):
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    return repr(float(v)) if isinstance(v, float) else str(v)


def prometheus_text(registry=None, exemplars=None):
    """Render ``registry`` (default: the process registry) in Prometheus
    text exposition format 0.0.4.

    ``exemplars=True`` (or ``PADDLE_TPU_METRICS_EXEMPLARS=1`` when the
    argument is left ``None``) appends OpenMetrics-style
    `` # {trace_id="..."} <value>`` exemplar suffixes to counter and
    histogram-bucket samples that have one recorded — the hook from a
    latency bucket straight to a distributed trace. Off by default:
    classic text-format scrapers reject the suffix."""
    registry = registry or get_registry()
    ex_on = _exemplars_enabled(exemplars)

    def ex_suffix(ex):
        return _fmt_exemplar(ex) if ex_on and ex else ""

    lines = []
    for m in registry.metrics():
        name = _sanitize_name(m.prom_name)
        try:
            d = m.data()
        except Exception:
            continue
        kind = d.get("type", "untyped")
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "counter":
            total = name if name.endswith("_total") else name + "_total"
            series = d.get("series", [])
            if not series:
                lines.append(
                    f"{total} {_fmt_value(d['value'])}"
                    f"{ex_suffix(d.get('exemplar'))}"
                )
            else:
                # one family must not mix a bare aggregate with labeled
                # children — sum(rate(...)) would double-count. Emit the
                # children; any unlabeled increments (mixed usage) go
                # out as a remainder sample with empty label values.
                for s in series:
                    lines.append(
                        f"{total}{_fmt_labels(s['labels'])} "
                        f"{_fmt_value(s['value'])}"
                        f"{ex_suffix(s.get('exemplar'))}"
                    )
                rest = d["value"] - sum(s["value"] for s in series)
                if rest:
                    # union of every child's label keys: a remainder
                    # labeled with only one child's keys would vanish
                    # from sum by(<other_key>) queries
                    blank = {
                        k: "" for s in series for k in s["labels"]
                    }
                    lines.append(
                        f"{total}{_fmt_labels(blank)} {_fmt_value(rest)}"
                    )
        elif kind == "gauge":
            for s in d.get("series", []):
                lines.append(
                    f"{name}{_fmt_labels(s['labels'])} "
                    f"{_fmt_value(s['value'])}"
                )
        elif kind == "histogram":
            series = d.get("series", [])
            if not series:
                for b in d.get("buckets", []):
                    le = b["le"]
                    le_s = ("+Inf" if math.isinf(le)
                            else _fmt_value(float(le)))
                    lines.append(
                        f'{name}_bucket{{le="{le_s}"}} {b["count"]}'
                        f"{ex_suffix(b.get('exemplar'))}"
                    )
                lines.append(f"{name}_sum {_fmt_value(d.get('sum', 0.0))}")
                lines.append(f"{name}_count {d.get('count', 0)}")
            else:
                # same no-mixing discipline as counters: a labeled
                # histogram family emits per-child buckets/_sum/_count
                # plus a blank-labeled remainder for any unlabeled
                # observes — never a bare aggregate alongside children
                # (sum(rate(..._bucket[5m])) would double-count).
                blank = {k: "" for s in series for k in s["labels"]}

                def emit_child(labels, buckets, csum, ccount, ex_ok=True):
                    for b in buckets:
                        le = b["le"]
                        le_s = ("+Inf" if math.isinf(le)
                                else _fmt_value(float(le)))
                        lb = dict(labels)
                        lb["le"] = le_s
                        ex = ex_suffix(b.get("exemplar")) if ex_ok else ""
                        lines.append(
                            f'{name}_bucket{_fmt_labels(lb)} '
                            f'{b["count"]}{ex}'
                        )
                    lines.append(
                        f"{name}_sum{_fmt_labels(labels)} "
                        f"{_fmt_value(csum)}"
                    )
                    lines.append(
                        f"{name}_count{_fmt_labels(labels)} {ccount}"
                    )

                for s in series:
                    emit_child(s["labels"], s["buckets"], s.get("sum", 0.0),
                               s.get("count", 0))
                rest_count = d.get("count", 0) - sum(
                    s.get("count", 0) for s in series
                )
                if rest_count:
                    rest_sum = d.get("sum", 0.0) - sum(
                        s.get("sum", 0.0) for s in series
                    )
                    rest_buckets = []
                    for i, b in enumerate(d.get("buckets", [])):
                        child_c = sum(
                            s["buckets"][i]["count"] for s in series
                        )
                        # remainder carries no exemplar: the parent's
                        # slot exemplar may belong to a labeled observe
                        rest_buckets.append(
                            {"le": b["le"], "count": b["count"] - child_c}
                        )
                    emit_child(blank, rest_buckets, rest_sum, rest_count,
                               ex_ok=False)
        else:
            for s in d.get("series", []):
                lines.append(
                    f"{name}{_fmt_labels(s['labels'])} "
                    f"{_fmt_value(s['value'])}"
                )
    return "\n".join(lines) + "\n"


# the labels block must be matched as a sequence of quoted pairs, NOT
# [^}]* — a '}' inside a quoted label value (repr'd dict/shape keys from
# trace-guard graphs) is legal exposition
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_LABELS_BLOCK = (
    r'(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*,?\s*)*'
)
# OpenMetrics exemplar suffix: `` # {labels} value [timestamp]`` — the
# same quoted-pair labels grammar as the sample's own block (an
# exemplar trace_id may hold escaped chars too), value/timestamp as
# bare tokens validated numerically after the match
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>" + _LABELS_BLOCK + r")\})?\s+(?P<value>[^\s#]+)"
    r"(?:\s+#\s*\{(?P<ex_labels>" + _LABELS_BLOCK + r")\}"
    r"\s+(?P<ex_value>[^\s#]+)(?:\s+(?P<ex_ts>[^\s#]+))?)?\s*$"
)
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label(v):
    # single pass, so an escaped backslash can never re-combine with the
    # following char into a bogus escape (\\n must stay backslash+n)
    return _UNESCAPE_RE.sub(
        lambda m: {"n": "\n", "r": "\r"}.get(m.group(1), m.group(1)), v
    )


def _parse_value(v, line):
    value = {"+Inf": math.inf, "-Inf": -math.inf, "NaN": math.nan}.get(v)
    if value is None:
        try:
            value = float(v)
        except ValueError:
            raise ValueError(
                f"malformed sample value {v!r} on line: {line!r}"
            ) from None
    return value


def parse_prometheus_text(text, exemplars=False):
    """Parse exposition text into ``{series_name: [(labels, value)]}``.

    Strict about sample-line shape (a malformed line raises ValueError,
    which is exactly what the smoke gate wants to catch); comment and
    blank lines are skipped. Exemplar suffixes (`` # {...} value``) are
    validated on EVERY line — a malformed exemplar is a clear,
    dedicated ValueError, never silently dropped; with
    ``exemplars=True`` the return is ``(series, exemplar_list)`` where
    each exemplar entry is ``{"series", "labels", "exemplar_labels",
    "value"}``."""
    out = {}
    found = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            if "#" in line:
                raise ValueError(
                    "malformed exemplar (expected "
                    "'# {label=\"v\",...} value [timestamp]') on "
                    f"line: {line!r}"
                )
            raise ValueError(f"malformed exposition line: {line!r}")
        labels = {}
        if m.group("labels"):
            for lm in _LABEL_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
        value = _parse_value(m.group("value"), line)
        name = m.group("name")
        out.setdefault(name, []).append((labels, value))
        if m.group("ex_value") is not None:
            ex_labels = {}
            for lm in _LABEL_RE.finditer(m.group("ex_labels") or ""):
                ex_labels[lm.group(1)] = _unescape_label(lm.group(2))
            ex_value = _parse_value(m.group("ex_value"), line)
            if m.group("ex_ts") is not None:
                _parse_value(m.group("ex_ts"), line)  # validate only
            found.append({
                "series": name,
                "labels": labels,
                "exemplar_labels": ex_labels,
                "value": ex_value,
            })
    return (out, found) if exemplars else out


class MetricsServer:
    """Optional ``/metrics`` endpoint over ``http.server`` (stdlib only).

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    The serving thread is a daemon: it never blocks process exit."""

    def __init__(self, port=0, host="127.0.0.1", registry=None):
        self.host = host
        self.port = int(port)
        self.registry = registry or get_registry()
        self._httpd = None
        self._thread = None

    def start(self):
        import http.server

        registry = self.registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet: no per-scrape stderr
                pass

            def _send(self, body, ctype):
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        self._send(
                            prometheus_text(registry),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/metrics.json":
                        self._send(
                            json.dumps(registry.snapshot(), default=str),
                            "application/json",
                        )
                    elif path == "/flight":
                        from .flight_recorder import get_flight_recorder

                        self._send(
                            json.dumps(
                                get_flight_recorder().bundle(
                                    reason="http:/flight"
                                ),
                                default=str,
                            ),
                            "application/json",
                        )
                    elif path == "/trace":
                        from .tracing import trace_payload

                        self._send(
                            json.dumps(trace_payload(), default=str),
                            "application/json",
                        )
                    else:
                        self.send_error(404)
                except Exception as e:  # a broken scrape must not kill
                    try:                # the serving thread
                        self.send_error(500, str(e))
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def url(self):
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def start_metrics_server(port=0, host="127.0.0.1", registry=None):
    """Start a daemon-thread /metrics endpoint; returns the server
    (``server.port`` holds the bound port, ``server.stop()`` ends it)."""
    return MetricsServer(port=port, host=host, registry=registry).start()
