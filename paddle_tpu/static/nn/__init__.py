"""paddle.static.nn — control-flow ops.

Reference parity: python/paddle/static/nn/control_flow.py (cond /
while_loop / switch_case / case — unverified, mount empty). TPU-first
redesign: these lower to XLA's structured control flow — ``lax.cond``,
``lax.while_loop``, ``lax.switch`` — compiled into on-device HLO
conditionals/loops (no host interpreter like the reference's
ConditionalBlock/While ops). With a concrete (eager) predicate they run
as ordinary Python with tape autograd; with a traced predicate they are
reverse-differentiable through whole-step jit (``cond``/``switch_case``
natively; ``while_loop`` is forward-only under reverse AD, an XLA
constraint — use ``lax.scan``-style bounded loops / unrolled Python loops
for trainable recurrences).
"""
from __future__ import annotations

from ...jit.dy2static import cond_impl, switch_impl, while_impl

__all__ = ["cond", "while_loop", "switch_case", "case"]


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Both callables take no arguments (close over what they need) and must
    return matching Tensor structures when ``pred`` is traced.
    """
    t = true_fn if true_fn is not None else (lambda: None)
    f = false_fn if false_fn is not None else (lambda: None)
    return cond_impl(pred, t, f, names=return_names, where="cond")


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """Repeat ``body(*loop_vars)`` while ``cond(*loop_vars)`` holds.

    ``loop_vars`` is a list/tuple; ``body`` must return the same number of
    values. Returns the final loop variables as a list (paddle contract).

    ``maximum_trip_count`` (TPU extension): bound the traced loop so it
    lowers to a fixed-length masked scan, which reverse-mode AD supports
    — required when the loop output is trained through (XLA cannot
    backprop an unbounded ``lax.while_loop``).
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError(
            "while_loop: loop_vars must be a non-empty list/tuple, got "
            f"{type(loop_vars).__name__}"
        )
    if not callable(cond) or not callable(body):
        raise TypeError("while_loop: cond and body must be callable")
    out = while_impl(
        cond, body, tuple(loop_vars), where="while_loop",
        maximum_trip_count=maximum_trip_count,
    )
    return list(out)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Run the branch whose index matches ``branch_index``; unmatched or
    out-of-range indices run ``default`` (paddle: the largest-index branch
    when no default is given)."""
    return switch_impl(
        branch_index, branch_fns, default=default, where="switch_case"
    )


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins condition chain (paddle.static.nn.case): pairs of
    (scalar bool Tensor, callable)."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    pairs = list(pred_fn_pairs)

    def build(i):
        if i == len(pairs):
            if default is None:
                # paddle: the last branch doubles as the default
                return pairs[-1][1]
            return default
        pred, fn = pairs[i]
        return lambda: cond_impl(
            pred, fn, build(i + 1), where="case"
        )

    return build(0)()
