"""Minimal paddle.static surface.

The reference's static-graph Program/Executor machinery (python/paddle/
static/ — unverified, mount empty) is replaced wholesale by jax.jit
(SURVEY.md §3.5): "static mode" == traced+compiled callables. What remains
meaningful here is InputSpec (shape/dtype contracts for jit.save/to_static)
and no-op guards for API compatibility.
"""
from __future__ import annotations

import numpy as np

from ..core.dtypes import convert_dtype


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={np.dtype(self.dtype).name}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def example(self, batch=1):
        """A zero example array matching this spec (None dims -> batch)."""
        import jax.numpy as jnp

        shape = [batch if (s is None or s < 0) else s for s in (self.shape or [])]
        return jnp.zeros(shape, self.dtype)


# imported last: static.nn pulls in jit (which needs InputSpec above)
from . import nn  # noqa: E402,F401  (control flow: cond/while_loop/...)
