"""Collective-schedule lint: the distributed-hang shape, caught offline.

A multi-rank program hangs when ranks disagree about WHICH collectives
run in WHAT order — one rank enters a psum its peers never issue and
the fleet waits forever. Every instance this repo has shipped (the PR 5
writer-thread collective, rank-gated barrier calls in early fleet
drafts) was caught by hand in review; these rules mechanize that
review at two levels:

- ``collective-divergence`` (jaxpr rule, wired into
  :func:`jaxpr_lint.lint_closed_jaxpr`): extracts the ORDERED sequence
  of collective primitives (+ axis names) per ``lax.cond`` /
  ``lax.switch`` branch — recursing through scan / while / shard_map /
  pjit sub-jaxprs with the same ``_walk_eqns`` walk the other graph
  rules use — and fires when two branches of one conditional emit
  different schedules. If the predicate can ever differ across ranks
  (and a traced predicate usually can), that graph is a deadlock with
  a repro rate. Branches on genuinely uniform predicates are the
  accept-with-reason case the baseline exists for.
- ``rank-conditional-collective`` (AST rule): a collective call
  lexically under a ``get_rank()`` / ``process_index()``-style
  conditional — only some ranks participate, the others hang. The
  coordinator idiom stays clean: point-to-point ops
  (``send/recv/isend/irecv``) are rank-addressed by design, and a
  conditional whose other branch issues the SAME collective (symmetric
  participation, different args) does not fire.
- ``collective-off-main-thread`` (AST rule): a collective call site
  reachable (bounded call-graph walk) from a ``threading.Thread``
  target — the exact PR 5 bug: a background writer thread issuing a
  collective races the main thread's own collective schedule, and two
  interleaved schedules on one device set is the same hang as a
  divergent branch.

Suppress AST findings inline with ``# tpu-lint: disable=<rule>`` on the
offending line or the line above (the shared ast_lint mechanism).
"""
from __future__ import annotations

import ast

from .ast_lint import _dotted, suppressed as _suppressed
from .findings import Finding, Report, Severity

# collectives whose names are unambiguous at any call site
_COLLECTIVE_CALLS = {
    "psum", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "alltoall", "all_reduce",
    "reduce_scatter", "barrier", "all_gather_object",
    "broadcast_object_list", "scatter_object_list",
}
# generic verbs that are collectives only under a distributed namespace
_COLLECTIVE_IF_DIST = {"broadcast", "reduce", "scatter", "gather"}
_DIST_PREFIXES = ("dist", "distributed", "comm", "communication",
                  "fleet", "collective")
# rank-addressed by design: the coordinator idiom's building blocks
_POINT_TO_POINT = {"send", "recv", "isend", "irecv"}

_RANK_CALLS = {"get_rank", "process_index", "local_rank",
               "get_local_rank", "rank"}

_THREAD_REACH_DEPTH = 3


# ======================================================================
# jaxpr side: collective-divergence
# ======================================================================
def _is_collective_prim(name):
    from .jaxpr_lint import _COLLECTIVE_PRIMS

    if name == "axis_index":
        return False  # reads the axis, never communicates
    if name.startswith("pbroadcast"):
        # jax's replication-typing adjustment (shard_map check_rep):
        # device-local, inserted asymmetrically per branch — never a
        # communicating collective, never part of the hang schedule
        return False
    return any(name.startswith(p) for p in _COLLECTIVE_PRIMS
               if p != "axis_index")


def collective_schedule(jaxpr):
    """Ordered tuple of ``prim(axes)`` strings for every collective in
    ``jaxpr``, recursing through sub-jaxprs in eqn order. For a nested
    cond the FIRST branch's schedule stands in (each divergent nested
    cond already fires its own finding)."""
    from .jaxpr_lint import _axis_names_of, _sub_jaxprs

    out = []
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if _is_collective_prim(prim):
            axes = _axis_names_of(eqn)
            out.append(f"{prim}({','.join(axes)})")
        subs = list(_sub_jaxprs(eqn))
        if prim == "cond" and subs:
            out.extend(collective_schedule(subs[0]))
        else:
            for sub in subs:
                out.extend(collective_schedule(sub))
    return tuple(out)


def check_eqn_divergence(eqn, graph, rep):
    """Fire ``collective-divergence`` when the branches of a cond /
    switch eqn emit different collective schedules."""
    from .jaxpr_lint import ClosedJaxpr, Jaxpr, _src

    if eqn.primitive.name != "cond":
        return
    branches = eqn.params.get("branches")
    if not branches:
        return
    schedules = []
    for b in branches:
        j = b.jaxpr if isinstance(b, ClosedJaxpr) else b
        if isinstance(j, Jaxpr):
            schedules.append(collective_schedule(j))
    if len(schedules) < 2 or len(set(schedules)) <= 1:
        return
    shown = sorted({"[" + " ".join(s or ("<none>",)) + "]"
                    for s in schedules})
    rep.add(Finding(
        rule="collective-divergence", severity=Severity.ERROR,
        message=(
            "cond/switch branches emit different collective schedules "
            + " vs ".join(shown)
            + " — ranks disagreeing on the predicate deadlock here; "
            "hoist the collective out of the branch or make the "
            "predicate provably uniform"
        ),
        graph=graph, where=_src(eqn),
        detail="cond:" + "!=".join(shown),
    ))


# ======================================================================
# AST side: rank-conditional-collective / collective-off-main-thread
# ======================================================================
def _collective_name(call):
    """The collective's name when ``call`` is a collective invocation,
    else None (point-to-point ops excluded — rank-addressed)."""
    name = _dotted(call.func)
    if name is None:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last in _COLLECTIVE_CALLS:
        return last
    if last in _COLLECTIVE_IF_DIST and len(parts) > 1 and any(
        p in _DIST_PREFIXES for p in parts[:-1]
    ):
        return last
    return None


def _collective_calls_in(node):
    """[(name, lineno)] for every collective call anywhere under
    ``node``."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            c = _collective_name(n)
            if c is not None:
                out.append((c, n.lineno))
    return out


def _is_rank_test(test):
    """True when an ``if`` test depends on the caller's rank: a
    ``get_rank()/process_index()``-style call, or a name/attribute
    whose last component mentions rank."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            name = _dotted(n.func)
            if name and name.split(".")[-1] in _RANK_CALLS:
                return True
        elif isinstance(n, (ast.Name, ast.Attribute)):
            name = _dotted(n)
            if name and "rank" in name.split(".")[-1].lower():
                return True
    return False


def _rank_conditional_findings(tree, rel, rep, lines):
    for node in ast.walk(tree):
        if not isinstance(node, ast.If) or not _is_rank_test(node.test):
            continue
        body_calls = [c for stmt in node.body
                      for c in _collective_calls_in(stmt)]
        else_calls = [c for stmt in node.orelse
                      for c in _collective_calls_in(stmt)]
        else_names = {c for c, _ in else_calls}
        body_names = {c for c, _ in body_calls}
        for calls, other in ((body_calls, else_names),
                             (else_calls, body_names)):
            for cname, lineno in calls:
                if cname in other:
                    continue  # symmetric participation: both sides call
                if _suppressed(lines, lineno,
                               "rank-conditional-collective"):
                    continue
                rep.add(Finding(
                    rule="rank-conditional-collective",
                    severity=Severity.ERROR,
                    message=(
                        f"collective `{cname}` under a rank conditional "
                        f"— only some ranks participate, the rest hang; "
                        f"use send/recv for coordinator work or run the "
                        f"collective on every rank"
                    ),
                    graph=rel, where=f"{rel}:{lineno}",
                    detail=f"rank-if:{cname}:{lineno}",
                ))


class _ModuleGraph:
    """Bare-name call graph of one module: functions/methods, the
    collective calls each makes directly, and thread-target entry
    points (``threading.Thread(target=...)``)."""

    def __init__(self, tree):
        self.direct = {}        # fn bare name -> [(collective, lineno)]
        self.calls = {}         # fn bare name -> set of called bare names
        self.thread_targets = []  # (target bare name, lineno)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(node)
            elif isinstance(node, ast.Call):
                self._scan_thread(node)

    def _scan_fn(self, fn):
        direct, called = [], set()
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            c = _collective_name(n)
            if c is not None:
                direct.append((c, n.lineno))
            name = _dotted(n.func)
            if name:
                called.add(name.split(".")[-1])
        self.direct.setdefault(fn.name, []).extend(direct)
        self.calls.setdefault(fn.name, set()).update(called)

    def _scan_thread(self, call):
        name = _dotted(call.func)
        if not name or name.split(".")[-1] != "Thread":
            return
        for kw in call.keywords:
            if kw.arg == "target":
                t = _dotted(kw.value)
                if t:
                    self.thread_targets.append(
                        (t.split(".")[-1], call.lineno)
                    )

    def reachable(self, entry, depth=_THREAD_REACH_DEPTH):
        seen, frontier = {entry}, {entry}
        for _ in range(depth):
            nxt = set()
            for fn in frontier:
                nxt |= {c for c in self.calls.get(fn, ())
                        if c in self.calls and c not in seen}
            if not nxt:
                break
            seen |= nxt
            frontier = nxt
        return seen


def _off_main_thread_findings(tree, rel, rep, lines):
    g = _ModuleGraph(tree)
    for target, t_line in g.thread_targets:
        if target not in g.calls:
            continue  # target defined elsewhere: out of this pass's view
        for fn in sorted(g.reachable(target)):
            for cname, lineno in g.direct.get(fn, ()):
                if _suppressed(lines, lineno,
                               "collective-off-main-thread"):
                    continue
                rep.add(Finding(
                    rule="collective-off-main-thread",
                    severity=Severity.ERROR,
                    message=(
                        f"collective `{cname}` in `{fn}` is reachable "
                        f"from threading.Thread target `{target}` "
                        f"(line {t_line}) — a background-thread "
                        f"collective interleaves with the main thread's "
                        f"schedule and deadlocks the fleet (the PR 5 "
                        f"writer-thread bug); move the collective to "
                        f"the main thread or hand the thread plain "
                        f"host data"
                    ),
                    graph=rel, where=f"{rel}:{lineno}",
                    detail=f"thread:{target}->{fn}:{cname}",
                ))


def lint_parsed(tree, lines, rel):
    """Both collective AST rules over an already-parsed module."""
    rep = Report()
    _rank_conditional_findings(tree, rel, rep, lines)
    _off_main_thread_findings(tree, rel, rep, lines)
    return rep


def lint_source(source, rel="<string>"):
    """Run both collective AST rules over one source string."""
    from .ast_lint import _parse_or_report

    tree, lines, rep = _parse_or_report(source, rel)
    if tree is None:
        return rep
    rep.extend(lint_parsed(tree, lines, rel))
    return rep


def lint_file(path, root=None):
    from .ast_lint import lint_one_file

    return lint_one_file(lint_parsed, path, root=root)


def lint_path(path, root=None, skip_dirs=None):
    """Recursively run the collective AST rules under ``path``."""
    from .ast_lint import DEFAULT_SKIP_DIRS, lint_tree

    return lint_tree(lint_parsed, path, root=root,
                     skip_dirs=skip_dirs or DEFAULT_SKIP_DIRS)
