"""Finding/report model shared by every lint pass.

A Finding is one hazard at one location: ``rule`` names the check,
``severity`` ranks it, ``where`` points at the source (user frame for
jaxpr rules, file:line for AST rules, function key for the trace
guard), and ``graph`` names the linted program so the same rule firing
in two graphs stays two findings. ``key()`` is the stable identity the
baseline matches on — deliberately line-number-free for jaxpr findings
(tracing moves lines; the hazard is per-graph-per-rule-per-detail).
"""
from __future__ import annotations

import dataclasses
import json


class Severity:
    ERROR = "error"      # correctness hazard (would be wrong/crash on chip)
    WARNING = "warning"  # perf hazard (runs, but slower than the hw allows)
    INFO = "info"        # worth knowing; never gates

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def rank(cls, s):
        return cls._ORDER.get(s, 99)


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str
    message: str
    graph: str = ""       # linted program name ("llama_fwd", "decode_step"…)
    where: str = ""       # provenance: file:line or function key
    detail: str = ""      # stable discriminator (var/dtype/axis/param name)

    def key(self):
        """Baseline identity: everything except the free-text message."""
        return f"{self.rule}|{self.graph}|{self.detail}"

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: d.get(k, "") for k in
                      ("rule", "severity", "message", "graph", "where",
                       "detail")})

    def __str__(self):
        loc = f" [{self.where}]" if self.where else ""
        g = f" ({self.graph})" if self.graph else ""
        return f"{self.severity.upper()} {self.rule}{g}: {self.message}{loc}"


class Report:
    """An ordered collection of findings with merge/serialize helpers."""

    def __init__(self, findings=None):
        self.findings = list(findings or [])

    def add(self, finding):
        self.findings.append(finding)

    def extend(self, findings):
        self.findings.extend(findings)

    def sorted(self):
        return sorted(
            self.findings,
            key=lambda f: (Severity.rank(f.severity), f.rule, f.graph,
                           f.detail),
        )

    def by_rule(self):
        out = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out

    def counts(self):
        out = {}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def to_json(self, indent=1):
        return json.dumps(
            {"findings": [f.to_dict() for f in self.sorted()],
             "counts": self.counts()},
            indent=indent,
        )

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)
