"""Static lint over closed jaxprs — catch chip hazards before the chip.

Every hazard class this repo has shipped so far was only discoverable
by *running* the graph; these rules find them by *walking* it. The walk
recurses through structured-control-flow sub-jaxprs (pjit, scan, while,
cond branches, shard_map, custom_vjp), so a hazard inside a decode scan
or a pipeline stage is reported with the same provenance as a top-level
one.

Rules (ids are stable; the baseline and inline suppressions key on
them):

- ``fp64-leak``      fp64/complex128 values in the graph (TPU has no
                     native fp64 — every such op runs emulated or
                     rejects at compile time) plus weak-typed f64
                     literals that silently widen neighbours.
- ``dtype-churn``    chained ``convert_element_type`` (A->B->C collapses
                     to one convert; A->B->A is pure waste) and
                     bulk narrow->wide upcasts above a byte threshold
                     (silent hot-path promotion, the flash-attention
                     mixed q/kv failure mode). INTENTIONAL int8/fp8
                     quant-dequant pairs are whitelisted when tagged —
                     issuing function name matching quant/dequant/fp8/
                     int8, or a ``# tpu-lint: quant`` marker on the
                     source line — so real narrow-dtype execution lands
                     with zero baseline growth.
- ``host-transfer``  host callbacks (``pure_callback``/``io_callback``/
                     ``debug_callback``) and ``device_put`` inside the
                     compiled region — each is a device stall.
- ``donation-miss``  large input buffers whose aval reappears in the
                     outputs undonated (optimizer state, KV slabs):
                     XLA must double-buffer them every step.
- ``collective-mesh-mismatch``  collectives whose axis names are not
                     axes of the installed ``parallel.mesh`` mesh (nor,
                     in auto mode, axes an EXPLICITLY installed
                     ``parallel.layout`` policy declares — the hybrid
                     layout's vocab-CE psum / pp state-sharding
                     collectives lint clean under a narrower installed
                     mesh; with no policy installed the rule stays
                     fully strict) — the graph can never run on the
                     fleet topology.
- ``broadcast-blowup``  non-scalar broadcasts that multiply bytes past
                     a threshold (materialized [B,H,S,S] masks etc.).
- ``collective-divergence``  cond/switch branches whose COLLECTIVE
                     SCHEDULES differ (rule body in
                     :mod:`collective_lint` — ranks disagreeing on the
                     predicate deadlock; the distributed-hang shape).
"""
from __future__ import annotations

import dataclasses
import re as _re

import numpy as np

import jax

from .findings import Finding, Report, Severity

try:  # jaxpr types moved around across jax versions
    from jax.extend.core import ClosedJaxpr, Jaxpr, Literal, Var  # noqa: F401
except Exception:  # pragma: no cover - older/newer layouts
    from jax.core import ClosedJaxpr, Jaxpr, Literal, Var  # noqa: F401


@dataclasses.dataclass
class LintConfig:
    """Thresholds for the graph rules; tests shrink them to force
    firings, the CLI uses the defaults."""

    check_fp64: bool = True
    min_donation_bytes: int = 1 << 20       # 1 MiB: opt state / KV slabs
    min_broadcast_bytes: int = 128 << 20    # materialized-mask scale
    broadcast_ratio: float = 64.0
    min_upcast_bytes: int = 32 << 20        # bulk narrow->wide promotion
    check_collective_divergence: bool = True
    mesh_axes: tuple | None = None          # None: use the global mesh
    #: auto mode only: accept axis names declared by an EXPLICITLY
    #: installed parallel.layout policy on top of the installed mesh's —
    #: a graph built for the hybrid layout (vocab-CE psum over mp, pp
    #: state-sharding collectives) lints clean even when the process
    #: currently holds a narrower mesh (e.g. the serving dp-only one).
    #: With no policy installed the rule keeps full strictness (the
    #: implicit default would whitelist every standard axis name), and
    #: explicit ``mesh_axes`` configs are honored verbatim.
    include_policy_axes: bool = True

    def resolved_mesh_axes(self):
        if self.mesh_axes is not None:
            return tuple(self.mesh_axes)
        from ..parallel import mesh as mesh_mod

        if mesh_mod.mesh_defined():
            axes = tuple(mesh_mod.get_mesh().axis_names)
            if self.include_policy_axes:
                from ..parallel import layout as layout_mod

                if layout_mod.policy_installed():
                    axes += tuple(
                        a for a in layout_mod.get_policy().axis_names()
                        if a not in axes
                    )
            return axes
        return None  # no mesh installed -> rule cannot judge, skip


_HOST_CALLBACK_PRIMS = {
    "pure_callback": Severity.ERROR,
    "io_callback": Severity.ERROR,
    "debug_callback": Severity.WARNING,  # debug_print et al.
    "device_put": Severity.WARNING,
}

# collective primitive -> params key holding the axis name(s); jax names
# drifted across versions (psum vs psum2), so match generously
_COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "ppermut", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
    "reduce_scatter_p", "pgather",
}

_WIDTH = {  # float widths for narrow->wide upcast detection
    "bfloat16": 2, "float16": 2, "float32": 4, "float64": 8,
}


def _src(eqn):
    """Best-effort user frame of an eqn: 'file:line (function)'."""
    try:
        from jax._src import source_info_util

        fr = source_info_util.user_frame(eqn.source_info)
        if fr is None:
            return ""
        return f"{fr.file_name}:{fr.start_line} ({fr.function_name})"
    except Exception:
        return ""


def _aval_str(aval):
    try:
        return f"{np.dtype(aval.dtype).name}[{','.join(map(str, aval.shape))}]"
    except Exception:
        return str(aval)


def _nbytes(aval):
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * np.dtype(
            aval.dtype
        ).itemsize
    except Exception:
        return 0


# quantization dtypes: a convert chain that passes through one of these
# is (when tagged) an intentional quant/dequant pair, not churn
_QUANT_DTYPES = ("int8", "uint8", "float8_e4m3fn", "float8_e5m2",
                 "float8_e4m3b11fnuz", "float8_e4m3fnuz",
                 "float8_e5m2fnuz")

# op-name pattern: converts issued from a function whose name says it
# quantizes are intentional by construction
_QUANT_FN_RE = _re.compile(r"quant|dequant|fp8|int8", _re.IGNORECASE)

_QUANT_MARKER = "# tpu-lint: quant"

_SRC_LINE_CACHE: dict = {}


def _source_line(where):
    """The source text at a ``file:line (function)`` provenance string
    (cached per file; empty on any miss)."""
    try:
        path, rest = where.split(":", 1)
        line_no = int(rest.split(" ", 1)[0])
    except (ValueError, AttributeError):
        return ""
    lines = _SRC_LINE_CACHE.get(path)
    if lines is None:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            lines = []
        _SRC_LINE_CACHE[path] = lines
    if 1 <= line_no <= len(lines):
        return lines[line_no - 1]
    return ""


def _quant_tagged(where, dtypes):
    """True when a convert chain is an INTENTIONAL int8/fp8
    quant-dequant pair: one of the chain's dtypes is a quant dtype AND
    the site is tagged — either the issuing function's name matches the
    quant pattern (quantize_kv, _fp8_dot, dequantize, ...) or the source
    line carries an explicit ``# tpu-lint: quant`` marker. Untagged
    chains through wide dtypes keep firing (real churn)."""
    if not any(np.dtype(d).name in _QUANT_DTYPES for d in dtypes):
        return False
    if "(" in (where or ""):
        fn_name = where.rsplit("(", 1)[1].rstrip(")")
        if _QUANT_FN_RE.search(fn_name):
            return True
    return _QUANT_MARKER in _source_line(where)


def _axis_names_of(eqn):
    """String axis names a collective eqn operates over (ints are
    positional vmap axes — not mesh axes, ignored)."""
    names = []
    for key in ("axes", "axis_name"):
        v = eqn.params.get(key)
        if v is None:
            continue
        for a in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(a, str):
                names.append(a)
    return names


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for b in v:
                if isinstance(b, ClosedJaxpr):
                    yield b.jaxpr
                elif isinstance(b, Jaxpr):
                    yield b


def _walk_eqns(jaxpr):
    """Yield (eqn, producer_map) over this jaxpr and every sub-jaxpr.
    producer_map maps Var -> producing eqn *within the same jaxpr*."""
    producers = {}
    for eqn in jaxpr.eqns:
        yield eqn, producers
        for ov in eqn.outvars:
            if isinstance(ov, Var):
                producers[ov] = eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


def lint_closed_jaxpr(closed, *, graph="", donated=None, config=None):
    """Run every graph rule over a ClosedJaxpr.

    ``donated``: optional sequence of bools aligned with
    ``closed.jaxpr.invars`` (True = buffer donated). Without it the
    donation rule treats every invar as undonated.
    """
    cfg = config or LintConfig()
    rep = Report()
    jaxpr = closed.jaxpr if isinstance(closed, ClosedJaxpr) else closed

    mesh_axes = cfg.resolved_mesh_axes()
    fp64_seen = set()
    churn_seen = set()
    upcast_bytes = 0
    upcast_example = ""

    # ---- constvars / literals ----------------------------------------
    if cfg.check_fp64:
        for cv in jaxpr.constvars:
            dt = getattr(cv.aval, "dtype", None)
            if dt is not None and np.dtype(dt).name in ("float64",
                                                        "complex128"):
                rep.add(Finding(
                    rule="fp64-leak", severity=Severity.ERROR,
                    message=f"fp64 constant captured by the graph: "
                            f"{_aval_str(cv.aval)}",
                    graph=graph, detail=f"const:{_aval_str(cv.aval)}",
                ))

    from .collective_lint import check_eqn_divergence

    for eqn, producers in _walk_eqns(jaxpr):
        prim = eqn.primitive.name

        # ---- collective-divergence -----------------------------------
        if cfg.check_collective_divergence and prim == "cond":
            check_eqn_divergence(eqn, graph, rep)

        # ---- fp64-leak -----------------------------------------------
        if cfg.check_fp64:
            for ov in eqn.outvars:
                dt = getattr(getattr(ov, "aval", None), "dtype", None)
                if dt is None:
                    continue
                name = np.dtype(dt).name
                if name in ("float64", "complex128"):
                    key = (prim, name, _src(eqn))
                    if key in fp64_seen:
                        continue
                    fp64_seen.add(key)
                    weak = bool(getattr(ov.aval, "weak_type", False))
                    rep.add(Finding(
                        rule="fp64-leak", severity=Severity.ERROR,
                        message=(
                            f"`{prim}` produces {name}"
                            + (" (weak-typed literal promotion)" if weak
                               else "")
                            + " — TPU has no native fp64"
                        ),
                        graph=graph, where=_src(eqn),
                        detail=f"{prim}:{name}",
                    ))

        # ---- dtype-churn ---------------------------------------------
        if prim == "convert_element_type":
            iv = eqn.invars[0]
            src_dt = np.dtype(iv.aval.dtype)
            dst_dt = np.dtype(eqn.params.get("new_dtype", src_dt))
            producer = producers.get(iv) if isinstance(iv, Var) else None
            if producer is not None and \
                    producer.primitive.name == "convert_element_type":
                first_dt = np.dtype(producer.invars[0].aval.dtype)
                path = (f"{first_dt.name}->{src_dt.name}->{dst_dt.name}")
                key = (path, _src(eqn))
                if key not in churn_seen:
                    churn_seen.add(key)
                    if _quant_tagged(_src(eqn),
                                     (first_dt, src_dt, dst_dt)):
                        # tagged int8/fp8 quant-dequant pair:
                        # intentional narrow-dtype execution, not churn
                        pass
                    else:
                        roundtrip = first_dt == dst_dt
                        rep.add(Finding(
                            rule="dtype-churn",
                            severity=Severity.WARNING,
                            message=(
                                f"chained convert {path} "
                                + ("is a round trip (pure waste)"
                                   if roundtrip
                                   else "collapses to one convert")
                            ),
                            graph=graph, where=_src(eqn), detail=path,
                        ))
            # bulk narrow->wide float promotion accounting
            sw, dw = _WIDTH.get(src_dt.name), _WIDTH.get(dst_dt.name)
            if sw and dw and dw > sw:
                nb = _nbytes(eqn.outvars[0].aval)
                upcast_bytes += nb
                if not upcast_example:
                    upcast_example = (
                        f"{src_dt.name}->{dst_dt.name} "
                        f"{_aval_str(eqn.outvars[0].aval)} at {_src(eqn)}"
                    )

        # ---- host-transfer -------------------------------------------
        if prim in _HOST_CALLBACK_PRIMS:
            rep.add(Finding(
                rule="host-transfer",
                severity=_HOST_CALLBACK_PRIMS[prim],
                message=f"`{prim}` inside the compiled region stalls the "
                        f"device on the host",
                graph=graph, where=_src(eqn), detail=f"{prim}@{_src(eqn)}",
            ))

        # ---- collective-mesh-mismatch --------------------------------
        if mesh_axes is not None and any(
            prim.startswith(p) for p in _COLLECTIVE_PRIMS
        ):
            for ax in _axis_names_of(eqn):
                if ax not in mesh_axes:
                    rep.add(Finding(
                        rule="collective-mesh-mismatch",
                        severity=Severity.ERROR,
                        message=(
                            f"collective `{prim}` over axis {ax!r} but the "
                            f"installed mesh has axes {list(mesh_axes)}"
                        ),
                        graph=graph, where=_src(eqn),
                        detail=f"{prim}:{ax}",
                    ))

        # ---- broadcast-blowup ----------------------------------------
        if prim == "broadcast_in_dim":
            out = eqn.outvars[0].aval
            inp = eqn.invars[0].aval
            in_size = int(np.prod(getattr(inp, "shape", ()) or (1,),
                                  dtype=np.int64))
            out_bytes = _nbytes(out)
            if (
                in_size > 1  # scalar broadcasts fuse; skip them
                and out_bytes >= cfg.min_broadcast_bytes
                and out_bytes / max(in_size * np.dtype(inp.dtype).itemsize,
                                    1) >= cfg.broadcast_ratio
            ):
                rep.add(Finding(
                    rule="broadcast-blowup", severity=Severity.WARNING,
                    message=(
                        f"broadcast {_aval_str(inp)} -> {_aval_str(out)} "
                        f"materializes {out_bytes >> 20} MiB in HBM"
                    ),
                    graph=graph, where=_src(eqn),
                    detail=f"{_aval_str(inp)}->{_aval_str(out)}",
                ))

    if upcast_bytes >= cfg.min_upcast_bytes:
        rep.add(Finding(
            rule="dtype-churn", severity=Severity.WARNING,
            message=(
                f"{upcast_bytes >> 20} MiB of narrow->wide float upcasts "
                f"in one graph (first: {upcast_example}) — check the hot "
                f"path keeps its storage dtype"
            ),
            graph=graph, detail=f"upcast-bytes:{upcast_bytes >> 20}MiB",
        ))

    # ---- donation-miss (top-level invars only) ------------------------
    donated = list(donated) if donated is not None else [False] * len(
        jaxpr.invars
    )
    out_avals = {}
    for ov in jaxpr.outvars:
        aval = getattr(ov, "aval", None)
        if aval is not None and getattr(aval, "shape", None) is not None:
            k = (tuple(aval.shape), np.dtype(aval.dtype).name)
            out_avals[k] = out_avals.get(k, 0) + 1
    # donated inputs pair with matching output slots FIRST — only the
    # slots left over can convict an undonated input
    for i, iv in enumerate(jaxpr.invars):
        if i < len(donated) and donated[i]:
            aval = getattr(iv, "aval", None)
            if aval is not None and getattr(aval, "shape", None) is not None:
                k = (tuple(aval.shape), np.dtype(aval.dtype).name)
                if out_avals.get(k, 0) > 0:
                    out_avals[k] -= 1
    for i, iv in enumerate(jaxpr.invars):
        aval = getattr(iv, "aval", None)
        if aval is None or getattr(aval, "shape", None) is None:
            continue
        if i < len(donated) and donated[i]:
            continue
        if _nbytes(aval) < cfg.min_donation_bytes:
            continue
        k = (tuple(aval.shape), np.dtype(aval.dtype).name)
        if out_avals.get(k, 0) > 0:
            out_avals[k] -= 1  # one output slot absorbs one candidate
            rep.add(Finding(
                rule="donation-miss", severity=Severity.WARNING,
                message=(
                    f"input #{i} {_aval_str(aval)} "
                    f"({_nbytes(aval) >> 20} MiB) matches an output aval "
                    f"but is not donated — XLA double-buffers it every "
                    f"step (donate_argnums)"
                ),
                graph=graph, detail=f"arg{i}:{_aval_str(aval)}",
            ))
    return rep


def _donated_flags(args, donate_argnums, static_argnums):
    """Per-leaf donated flags aligned with make_jaxpr's flattened
    invars (static args contribute no invars)."""
    donate = set(donate_argnums or ())
    static = set(static_argnums or ())
    flags = []
    for i, a in enumerate(args):
        if i in static:
            continue
        leaves = jax.tree_util.tree_leaves(a)
        flags.extend([i in donate] * len(leaves))
    return flags


def lint_fn(fn, *args, graph="", donate_argnums=(), static_argnums=(),
            config=None, **kwargs):
    """Trace ``fn`` with the example args and lint the resulting graph.

    ``donate_argnums`` describes the donation the *production* call site
    uses (the serving engine donates on accelerators only — pass what
    the chip path passes, or the donation rule reports its CPU-gated
    misses)."""
    closed = jax.make_jaxpr(fn, static_argnums=tuple(static_argnums))(
        *args, **kwargs
    )
    kw_leaves = sum(
        len(jax.tree_util.tree_leaves(v)) for v in kwargs.values()
    )
    donated = _donated_flags(args, donate_argnums, static_argnums)
    donated += [False] * kw_leaves
    return lint_closed_jaxpr(
        closed, graph=graph or getattr(fn, "__name__", "fn"),
        donated=donated, config=config,
    )


def lint_jitted(jitted, *args, graph="", config=None, **kwargs):
    """Lint an existing ``jax.jit``-wrapped callable, reading its real
    donation flags from the lowering (``lower().args_info``)."""
    donated = None
    try:
        info = jitted.lower(*args, **kwargs).args_info
        donated = [
            bool(getattr(leaf, "donated", False))
            for leaf in jax.tree_util.tree_leaves(info)
        ]
    except Exception:
        pass
    closed = jax.make_jaxpr(jitted)(*args, **kwargs)
    return lint_closed_jaxpr(
        closed, graph=graph or getattr(jitted, "__name__", "jitted"),
        donated=donated, config=config,
    )
