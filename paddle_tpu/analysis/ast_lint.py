"""AST lint: Python-level hazards that never make it into a jaxpr.

The jaxpr rules see what *traced*; these rules see what would make the
trace wrong or impossible in the first place, by walking the source of
functions compiled with ``jax.jit`` (decorator form,
``functools.partial(jax.jit, ...)`` form, or module-level
``name = jax.jit(fn)`` assignment):

- ``traced-branch``  Python ``if``/``while`` on a traced parameter —
  inside jit this either crashes (ConcretizationTypeError) or silently
  bakes one branch in at trace time. Shape/dtype/None/isinstance tests
  are recognized as static and allowed. (``to_static`` functions are
  exempt: the dy2static pass converts their branches.)
- ``host-sync-in-jit``  ``.numpy()`` / ``.item()`` / ``.tolist()`` /
  ``float(param)``-style host pulls inside a jit region: a forced
  device sync per call, or a trace-time crash.
- ``missing-static-argnums``  a parameter used where Python needs a
  concrete value (``range(param)``, shape arguments to
  ``zeros/ones/full/arange``) without being listed in
  ``static_argnums``/``static_argnames``.

Suppress a finding inline with ``# tpu-lint: disable=<rule>`` (or
``disable=all``) on the offending line or the line above.
"""
from __future__ import annotations

import ast
import os

from .findings import Finding, Report, Severity

_BENIGN_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
_HOST_SYNC_METHODS = {"numpy", "item", "tolist", "copy_to_cpu"}
_SHAPE_BUILDERS = {"zeros", "ones", "full", "empty", "arange", "linspace",
                   "eye"}


def suppressed(lines, lineno, rule):
    """The one `# tpu-lint: disable=<rule>` parser every source pass
    shares: a finding is suppressed by a disable comment on its own
    line or the line above (``disable=all`` suppresses everything; a
    malformed bare ``disable=`` suppresses nothing)."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            text = lines[ln - 1]
            if "tpu-lint:" in text and "disable=" in text:
                tail = text.split("disable=", 1)[1].split()
                rules = tail[0].split(",") if tail else []
                if rule in rules or "all" in rules:
                    return True
    return False


def _dotted(node):
    """'jax.jit' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_elts(node):
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = node.elts
    else:
        elts = [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.append(e.value)
    return out


def _str_elts(node):
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = node.elts
    else:
        elts = [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append(e.value)
    return out


def _jit_call_info(call):
    """If ``call`` is a jax.jit(...) invocation, return its static
    argnums/argnames, else None."""
    name = _dotted(call.func)
    if name not in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return None
    static_nums, static_names = [], []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            static_nums = _int_elts(kw.value)
        elif kw.arg == "static_argnames":
            static_names = _str_elts(kw.value)
    return static_nums, static_names


def _decorator_jit_info(fn):
    """(static_argnums, static_argnames) if ``fn`` is jit-decorated."""
    for dec in fn.decorator_list:
        name = _dotted(dec)
        if name in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return [], []
        if isinstance(dec, ast.Call):
            info = _jit_call_info(dec)
            if info is not None:
                return info
            # functools.partial(jax.jit, static_argnums=...)
            if _dotted(dec.func) in ("functools.partial", "partial") and \
                    dec.args and _dotted(dec.args[0]) in (
                        "jax.jit", "jit"):
                nums, names = [], []
                for kw in dec.keywords:
                    if kw.arg == "static_argnums":
                        nums = _int_elts(kw.value)
                    elif kw.arg == "static_argnames":
                        names = _str_elts(kw.value)
                return nums, names
    return None


def _module_jit_assignments(tree):
    """{func_name: (static_argnums, static_argnames)} for module-level
    ``jitted = jax.jit(fn, ...)`` assignments."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            info = _jit_call_info(node.value)
            if info is not None and node.value.args and isinstance(
                node.value.args[0], ast.Name
            ):
                out[node.value.args[0].id] = info
    return out


class _FnLinter(ast.NodeVisitor):
    """Lint one jit-compiled function body."""

    def __init__(self, fn, static_nums, static_names, rel, rep, lines):
        args = fn.args
        # static_argnums index the full positional signature (jax.jit on
        # an unbound method counts `self` as arg 0), so resolve indices
        # BEFORE dropping self/cls from the tracked set
        names = [a.arg for a in args.posonlyargs + args.args]
        static = {names[i] for i in static_nums if 0 <= i < len(names)}
        static |= set(static_names)
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        self.params = set(names + [a.arg for a in args.kwonlyargs])
        self.traced = self.params - static
        self.fn = fn
        self.rel = rel
        self.rep = rep
        self.lines = lines

    # ------------------------------------------------------------- helpers
    def _suppressed(self, lineno, rule):
        return suppressed(self.lines, lineno, rule)

    def _add(self, rule, severity, message, node, detail):
        if self._suppressed(node.lineno, rule):
            return
        self.rep.add(Finding(
            rule=rule, severity=severity, message=message,
            graph=self.rel, where=f"{self.rel}:{node.lineno}",
            detail=f"{self.fn.name}:{detail}",
        ))

    def _traced_uses(self, node, benign=False):
        """Names of traced params used in value (non-static) position."""
        hits = []
        if isinstance(node, ast.Name):
            if not benign and node.id in self.traced:
                hits.append(node.id)
            return hits
        if isinstance(node, ast.Attribute):
            sub_benign = benign or node.attr in _BENIGN_ATTRS
            return self._traced_uses(node.value, sub_benign)
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in ("isinstance", "len", "getattr", "hasattr",
                         "callable", "type"):
                benign = True
            for child in list(node.args) + [kw.value for kw in
                                            node.keywords]:
                hits += self._traced_uses(child, benign)
            if isinstance(node.func, ast.Attribute):
                hits += self._traced_uses(node.func.value, benign)
            return hits
        if isinstance(node, ast.Compare):
            all_ident = all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            )
            for child in [node.left] + node.comparators:
                hits += self._traced_uses(child, benign or all_ident)
            return hits
        for child in ast.iter_child_nodes(node):
            hits += self._traced_uses(child, benign)
        return hits

    # -------------------------------------------------------------- visits
    def _check_branch(self, node, kind):
        for name in sorted(set(self._traced_uses(node.test))):
            self._add(
                "traced-branch", Severity.ERROR,
                f"Python `{kind}` on traced parameter {name!r} inside a "
                f"jit function — use lax.cond/lax.while_loop, or mark "
                f"{name!r} static (static_argnums)",
                node, f"{kind}:{name}",
            )
        self.generic_visit(node)

    def visit_If(self, node):
        self._check_branch(node, "if")

    def visit_While(self, node):
        self._check_branch(node, "while")

    def visit_Call(self, node):
        fname = _dotted(node.func)
        # .numpy()/.item()/.tolist() on anything inside a jit region
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _HOST_SYNC_METHODS:
            self._add(
                "host-sync-in-jit", Severity.ERROR,
                f"`.{node.func.attr}()` inside a jit function forces a "
                f"host sync (or fails to trace)",
                node, f"sync:{node.func.attr}",
            )
        # float(x)/int(x)/bool(x)/np.asarray(x) pulling a traced param
        if fname in ("float", "int", "bool", "np.asarray",
                     "numpy.asarray", "np.array", "numpy.array"):
            for name in sorted(set(
                h for a in node.args for h in self._traced_uses(a)
            )):
                self._add(
                    "host-sync-in-jit", Severity.ERROR,
                    f"`{fname}({name})` concretizes a traced value "
                    f"inside a jit function",
                    node, f"concretize:{fname}:{name}",
                )
        # range(param) / shape-builder(param): needs a static value
        needs_static = fname == "range" or (
            fname is not None
            and fname.rsplit(".", 1)[-1] in _SHAPE_BUILDERS
            and fname.rsplit(".", 1)[0] in ("jnp", "jax.numpy", "np",
                                            "numpy")
        )
        if needs_static:
            check_args = node.args if fname == "range" else node.args[:1]
            for name in sorted(set(
                h for a in check_args for h in self._traced_uses(a)
            )):
                self._add(
                    "missing-static-argnums", Severity.ERROR,
                    f"parameter {name!r} feeds `{fname}(...)` which needs "
                    f"a concrete value — add it to static_argnums",
                    node, f"static:{fname}:{name}",
                )
        self.generic_visit(node)


def _parse_or_report(source, rel):
    """(tree, lines, Report) — tree is None when the source does not
    parse, with the single parse-error finding already in the Report.
    The shared front half of every source pass."""
    rep = Report()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        rep.add(Finding(
            rule="parse-error", severity=Severity.INFO,
            message=f"could not parse: {e}", graph=rel, where=rel,
            detail="parse",
        ))
        return None, [], rep
    return tree, source.splitlines(), rep


def lint_parsed(tree, lines, rel):
    """The jit-hazard rules over an already-parsed module."""
    rep = Report()
    assigned = _module_jit_assignments(tree)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = _decorator_jit_info(node)
        if info is None:
            info = assigned.get(node.name)
        if info is None:
            continue
        nums, names = info
        _FnLinter(node, nums, names, rel, rep, lines).visit(node)
    return rep


def lint_source(source, rel="<string>"):
    """Lint one Python source string. Returns a Report."""
    tree, lines, rep = _parse_or_report(source, rel)
    if tree is None:
        return rep
    rep.extend(lint_parsed(tree, lines, rel))
    return rep


DEFAULT_SKIP_DIRS = ("__pycache__", ".git", "build", "dist")


def lint_one_file(passes, path, root=None):
    """Run one or more ``lint_parsed(tree, lines, rel)``-shaped passes
    over one file: ONE read, ONE parse, one parse-error finding no
    matter how many passes ride along. Shared by every source-level
    lint module."""
    if callable(passes):
        passes = (passes,)
    rel = os.path.relpath(path, root) if root else path
    try:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
    except OSError as e:
        rep = Report()
        rep.add(Finding(
            rule="parse-error", severity=Severity.INFO,
            message=f"could not read: {e}", graph=rel, where=rel,
            detail="read",
        ))
        return rep
    tree, lines, rep = _parse_or_report(src, rel)
    if tree is None:
        return rep
    for fn in passes:
        rep.extend(fn(tree, lines, rel))
    return rep


def lint_tree(passes, path, root=None, skip_dirs=DEFAULT_SKIP_DIRS):
    """Run one or more ``lint_parsed``-shaped passes over every .py
    under ``path`` — one directory walk, one read and one parse per
    file no matter how many passes ride along (the CLI runs three)."""
    root = root or path
    rep = Report()
    if os.path.isfile(path):
        rep.extend(lint_one_file(passes, path,
                                 root=os.path.dirname(path)))
        return rep
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in skip_dirs and not d.startswith(".")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rep.extend(lint_one_file(
                    passes, os.path.join(dirpath, fn), root=root
                ))
    return rep


def lint_file(path, root=None):
    return lint_one_file(lint_parsed, path, root=root)


def lint_path(path, root=None, skip_dirs=DEFAULT_SKIP_DIRS):
    """Recursively lint every .py file under ``path``."""
    return lint_tree(lint_parsed, path, root=root, skip_dirs=skip_dirs)
