"""Runtime lock sentinel — the dynamic half of the concurrency lint.

:mod:`concurrency_lint` proves lock-order properties about code it can
see; this module catches the inversions that only EXIST at runtime
(locks spread across classes, orders that depend on which callback
fired first) by instrumenting the locks themselves:

- :class:`SentinelLock` wraps a ``threading.Lock``/``RLock`` and keeps
  a per-thread stack of held locks. Acquiring B while holding A records
  the A->B edge in one process-global order graph; the first time the
  REVERSED edge is observed — any thread, any time earlier — the
  sentinel emits a ``lock-order-inversion`` Finding with both witness
  stacks. That is a deadlock that simply hasn't hit its interleaving
  yet, caught without hanging anything.

  Graph nodes are lock CLASSES (``ClassName.attr``), not instances —
  the lockdep discipline: ordering rules are properties of the code,
  and two instances of one class taking inconsistent class-level
  orders is a latent deadlock the moment the instances coincide (it
  also keeps the metric label space bounded). The deliberate trade:
  an inversion between two DIFFERENT instances of the same class is
  not separable from reentrancy and goes unreported.
- Releases are timed: a hold longer than ``long_hold_s`` is a
  ``lock-long-hold`` finding (the runtime twin of
  ``blocking-call-under-lock``).
- Everything is published: ``paddle_analysis_lock_inversions_total`` /
  ``paddle_analysis_lock_long_holds_total`` counters plus a
  flight-recorder event per detection, so a chaos run's bundle shows
  WHERE the ordering went wrong.

Opt-in, zero hot-path cost when off: :func:`maybe_instrument` is called
by the threaded runtimes' constructors and does nothing unless
``PADDLE_TPU_LOCK_SENTINEL=1`` (or :func:`instrument_locks` is called
explicitly — tests and the chaos smokes do). Instrumentation wraps the
object's lock attributes in place; locks already captured by a
``threading.Condition`` attribute are skipped (the condition holds a
reference to the RAW lock — wrapping would split the two into
different objects and break ``wait()``).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
import traceback

from .findings import Finding, Severity

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

DEFAULT_LONG_HOLD_S = 1.0


def enabled():
    """True when the env var arms the sentinel process-wide."""
    return os.environ.get("PADDLE_TPU_LOCK_SENTINEL", "").strip() \
        not in ("", "0", "false", "False")


def _call_site(skip_module=True):
    """'file:line (function)' of the frame that touched the lock —
    first frame outside this module."""
    here = os.path.basename(__file__)
    for fr in reversed(traceback.extract_stack(limit=12)):
        if skip_module and os.path.basename(fr.filename) == here:
            continue
        return f"{fr.filename}:{fr.lineno} ({fr.name})"
    return "<unknown>"


class LockSentinel:
    """Process-global order graph + findings sink for instrumented
    locks. One instance per process (``get_sentinel``); tests swap a
    fresh one in with ``use_sentinel``."""

    def __init__(self, *, long_hold_s=None, clock=time.monotonic,
                 registry=None, recorder=None):
        if long_hold_s is None:
            # constructed at module import (the process-wide default
            # sentinel): a malformed env value must degrade to the
            # default, not crash every `import paddle_tpu.analysis`
            try:
                long_hold_s = float(os.environ.get(
                    "PADDLE_TPU_LOCK_LONG_HOLD_S", DEFAULT_LONG_HOLD_S
                ))
            except (TypeError, ValueError):
                long_hold_s = DEFAULT_LONG_HOLD_S
        self.long_hold_s = float(long_hold_s)
        self.clock = clock
        self._registry = registry
        self._recorder = recorder
        self._lock = threading.Lock()   # guards the graph + findings
        self._tls = threading.local()
        self._edges = {}        # (a, b) -> first-witness call site
        self._fired_pairs = set()
        self._long_hold_fired = set()
        self._tokens = itertools.count(1)
        # holds released by a DIFFERENT thread than their acquirer (a
        # legal Lock hand-off): the acquirer's TLS entry is stale and
        # must not feed the order graph — purged lazily by token
        self._cancelled = set()
        self.findings = []
        self.instrumented = []  # lock names, registration order

    # ------------------------------------------------------------ plumbing
    def _registry_or_default(self):
        if self._registry is not None:
            return self._registry
        from ..observability import get_registry

        return get_registry()

    def _count(self, name, help_text, **labels):
        try:
            self._registry_or_default().counter(name, help=help_text)\
                .inc(**labels)
        except Exception:
            pass

    def _note(self, event, **info):
        try:
            rec = self._recorder
            if rec is None:
                from ..observability import get_flight_recorder

                rec = get_flight_recorder()
            rec.note(event, **info)
        except Exception:
            pass

    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        elif held and self._cancelled:
            # purge entries whose hold was released on ANOTHER thread
            # (Lock hand-off): they no longer protect anything here
            with self._lock:
                held[:] = [e for e in held
                           if e[3] not in self._cancelled]
        return held

    # ------------------------------------------------------------- events
    def note_acquired(self, name):
        """Called by a SentinelLock AFTER its inner lock is acquired.
        Returns the hold token the matching release must present."""
        held = self._held()
        site = _call_site()
        finding = None
        token = next(self._tokens)
        with self._lock:
            for h, _t0, h_site, _tok in held:
                if h == name:
                    continue  # reentrant RLock hold
                self._edges.setdefault((h, name),
                                       f"{h_site} -> {site}")
                rev = self._edges.get((name, h))
                pair = tuple(sorted((h, name)))
                if rev is not None and pair not in self._fired_pairs:
                    self._fired_pairs.add(pair)
                    finding = Finding(
                        rule="lock-order-inversion",
                        severity=Severity.ERROR,
                        message=(
                            f"runtime lock-order inversion: this thread "
                            f"acquired {name!r} while holding {h!r}, "
                            f"but the opposite order was also observed "
                            f"({name!r} then {h!r} at {rev}) — the two "
                            f"interleavings deadlock; current site: "
                            f"{site}"
                        ),
                        graph="runtime", where=site,
                        detail=f"runtime:{pair[0]}<->{pair[1]}",
                    )
                    self.findings.append(finding)
        held.append((name, self.clock(), site, token))
        if finding is not None:
            self._count(
                "paddle_analysis_lock_inversions_total",
                "runtime lock-order inversions seen by the sentinel, "
                "by lock pair",
                pair=f"{finding.detail}",
            )
            self._note("lock_inversion", detail=finding.detail,
                       where=site)
        return token

    def note_released(self, name, token=None):
        """Pop this thread's matching hold. A release whose token was
        acquired on a DIFFERENT thread (Lock hand-off) cancels that
        token instead, so the acquirer's stale entry is purged on its
        next touch rather than poisoning its order graph."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name and (token is None
                                       or held[i][3] == token):
                _, t0, site, _tok = held.pop(i)
                dur = self.clock() - t0
                if dur > self.long_hold_s:
                    self._long_hold(name, dur, site)
                return
        if token is not None:
            with self._lock:
                self._cancelled.add(token)

    def _long_hold(self, name, dur, site):
        with self._lock:
            first = name not in self._long_hold_fired
            if first:
                self._long_hold_fired.add(name)
                self.findings.append(Finding(
                    rule="lock-long-hold", severity=Severity.WARNING,
                    message=(
                        f"lock {name!r} held {dur:.3f}s (> "
                        f"{self.long_hold_s:.3f}s) — acquired at "
                        f"{site}; every contending thread stalled that "
                        f"long"
                    ),
                    graph="runtime", where=site,
                    detail=f"runtime:long-hold:{name}",
                ))
        self._count(
            "paddle_analysis_lock_long_holds_total",
            "lock holds exceeding the sentinel's long-hold threshold, "
            "by lock",
            lock=name,
        )
        if first:
            self._note("lock_long_hold", lock=name,
                       seconds=round(dur, 4), where=site)

    # ------------------------------------------------------------ readouts
    def inversions(self):
        with self._lock:
            return [f for f in self.findings
                    if f.rule == "lock-order-inversion"]

    def long_holds(self):
        with self._lock:
            return [f for f in self.findings
                    if f.rule == "lock-long-hold"]

    def edge_count(self):
        with self._lock:
            return len(self._edges)

    def reset(self):
        with self._lock:
            self._edges.clear()
            self._fired_pairs.clear()
            self._long_hold_fired.clear()
            self.findings.clear()


class SentinelLock:
    """Drop-in wrapper over a ``threading.Lock``/``RLock`` that reports
    acquire/release to the sentinel. Supports the full lock protocol
    (``with``, ``acquire(blocking=, timeout=)``, ``locked()``) so it
    can sit wherever the raw lock sat."""

    __slots__ = ("_inner", "name", "_sentinel", "_active")

    def __init__(self, inner, name, sentinel=None):
        self._inner = inner
        self.name = name
        self._sentinel = sentinel or get_sentinel()
        # hold tokens, acquisition order. Mutated only while the inner
        # lock is held (append post-acquire, pop pre-release), so the
        # lock itself serializes access — including a hand-off release
        # from a thread that never acquired.
        self._active = []

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._active.append(
                self._sentinel.note_acquired(self.name)
            )
        return ok

    def release(self):
        token = self._active.pop() if self._active else None
        self._sentinel.note_released(self.name, token)
        self._inner.release()

    def locked(self):
        inner = self._inner
        fn = getattr(inner, "locked", None)
        if fn is not None:
            return fn()
        # RLock grows .locked() only in py3.14; _is_owned covers the
        # own-thread case (a reentrant probe would lie), then probe
        # without touching the sentinel bookkeeping (a query, not a
        # real hold)
        owned = getattr(inner, "_is_owned", None)
        if owned is not None and owned():
            return True
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"SentinelLock({self.name!r}, {self._inner!r})"


def instrument_locks(obj, *, name=None, sentinel=None, attrs=None):
    """Wrap ``obj``'s lock attributes in :class:`SentinelLock`s, in
    place. Returns the list of instrumented lock names
    (``ClassName.attr``). Skips locks a ``threading.Condition``
    attribute of the same object wraps (the condition keeps a raw-lock
    reference that instrumentation cannot follow), and locks that are
    already instrumented."""
    sent = sentinel or get_sentinel()
    prefix = name or type(obj).__name__
    cond_locks = set()
    attr_names = attrs or [a for a in vars(obj)]
    for a in attr_names:
        v = getattr(obj, a, None)
        if isinstance(v, threading.Condition):
            cond_locks.add(id(v._lock))
    done = []
    for a in attr_names:
        v = getattr(obj, a, None)
        if isinstance(v, SentinelLock) or not isinstance(
            v, _LOCK_TYPES
        ):
            continue
        if id(v) in cond_locks:
            continue
        lock_name = f"{prefix}.{a}"
        setattr(obj, a, SentinelLock(v, lock_name, sentinel=sent))
        done.append(lock_name)
    with sent._lock:
        sent.instrumented.extend(done)
    if done:
        try:
            sent._registry_or_default().gauge(
                "paddle_analysis_lock_instrumented",
                help="locks currently wrapped by the runtime sentinel",
            ).set(float(len(sent.instrumented)))
        except Exception:
            pass
    return done


def maybe_instrument(obj, *, name=None):
    """Constructor seam for the threaded runtimes: a no-op unless the
    ``PADDLE_TPU_LOCK_SENTINEL`` env var arms the sentinel."""
    if not enabled():
        return []
    return instrument_locks(obj, name=name)


# one process-wide sentinel: lock order is a process property
_SENTINEL = LockSentinel()


def get_sentinel() -> LockSentinel:
    return _SENTINEL


class use_sentinel:
    """Context manager installing a replacement sentinel (tests)."""

    def __init__(self, sentinel):
        self.sentinel = sentinel
        self._prev = None

    def __enter__(self):
        global _SENTINEL
        self._prev, _SENTINEL = _SENTINEL, self.sentinel
        return self.sentinel

    def __exit__(self, *exc):
        global _SENTINEL
        _SENTINEL = self._prev
        return False
