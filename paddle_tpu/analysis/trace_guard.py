"""Recompilation guard + leaked-tracer detection (runtime side of the
linter).

A TPU program that recompiles under drifting shapes/dtypes spends
seconds of wall clock per signature while the chip idles — the exact
failure mode ``serving``'s shape bucketing exists to prevent. The guard
watches compile-cache growth two ways:

- **explicit**: compile-cache owners (``jit.api.StaticFunction``,
  ``models.generation``'s per-net cache, the serving engine's bucket
  maps) call :func:`record_compile` with their cache key + the new
  signature on every miss.
- **polling**: any ``jax.jit``-wrapped callable can be registered with
  :func:`watch`; :func:`check` diffs its ``_cache_size()`` against the
  last observation, so recompiles that happen *inside* jax's own cache
  (shape drift invisible to the wrapper) are still counted.

When one function crosses ``max_compiles`` distinct signatures the
guard emits a ``recompile-storm`` Finding, forwards it to every
subscribed callback (the serving engine turns it into a
``profiler.record_span`` so storms land in chrome traces), and bumps
the profiler's lint-event counters so ``Profiler.summary()`` shows it.

Leaked-tracer detection (:func:`find_leaked_tracers`) walks any
pytree/Layer for ``jax.core.Tracer`` instances — the signature of a
trace that escaped its ``jit`` (the write-back pattern in
``generation.generate`` exists to prevent exactly this).
"""
from __future__ import annotations

import threading

import jax

from .findings import Finding, Severity

DEFAULT_MAX_COMPILES = 8


class TraceGuard:
    """Counts distinct compile signatures per function key."""

    def __init__(self, max_compiles=DEFAULT_MAX_COMPILES):
        self.max_compiles = int(max_compiles)
        self._sigs = {}      # key -> list of signatures, insertion order
        self._watched = {}   # name -> (jitted fn, last seen cache size)
        self._fired = set()  # keys that already produced a storm finding
        self.findings = []
        self._callbacks = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ explicit
    def record_compile(self, key, signature, origin=""):
        """Report a compile-cache MISS for ``key`` with ``signature``.
        Returns the storm Finding the miss triggered, else None."""
        with self._lock:
            sigs = self._sigs.setdefault(key, [])
            if signature in sigs:
                return None
            sigs.append(signature)
            n = len(sigs)
            if n <= self.max_compiles or key in self._fired:
                return None
            self._fired.add(key)
            recent = sigs[-3:]
        return self._fire(key, n, recent, origin)

    # ------------------------------------------------------------- polling
    def watch(self, name, jitted):
        """Track a jax.jit-wrapped callable's internal compile cache.
        The size at watch time is the baseline: only growth beyond it
        counts toward a storm."""
        with self._lock:
            self._watched[name] = [jitted, self._cache_size(jitted)]

    def unwatch(self, name):
        with self._lock:
            self._watched.pop(name, None)

    @staticmethod
    def _cache_size(jitted):
        try:
            return int(jitted._cache_size())
        except Exception:
            return 0

    def check(self):
        """Poll watched functions; returns new storm findings. Growth
        is measured against the baseline cache size recorded by
        ``watch()``/``reset()`` — entries compiled before watching are
        not this guard's storms."""
        fired = []
        with self._lock:
            items = list(self._watched.items())
        for name, slot in items:
            size = self._cache_size(slot[0])
            with self._lock:
                grown = size - slot[1]  # slot[1]: baseline at watch/reset
                if grown <= self.max_compiles or name in self._fired:
                    continue
                self._fired.add(name)
            fired.append(self._fire(name, grown, [], "jit-cache-poll"))
        return [f for f in fired if f is not None]

    # ------------------------------------------------------------- plumbing
    def on_fire(self, callback):
        """Subscribe ``callback(finding)`` to storm events."""
        self._callbacks.append(callback)
        return callback

    def _fire(self, key, n, recent, origin):
        detail = f"{key}:{n}"
        f = Finding(
            rule="recompile-storm", severity=Severity.WARNING,
            message=(
                f"{key!r} compiled {n} distinct signatures "
                f"(max {self.max_compiles}) — drifting shapes/dtypes; "
                f"bucket the inputs or mark them static"
                + (f"; recent: {recent}" if recent else "")
            ),
            graph=str(key), where=origin, detail=detail,
        )
        # under the lock: reset() clears this list under it, and an
        # unlocked append would race that clear (found by the repo's
        # own unlocked-shared-write pass)
        with self._lock:
            self.findings.append(f)
        from .. import profiler

        profiler.record_lint_event(f"lint::recompile-storm::{key}")
        # unified telemetry: storms are an alertable series, not only a
        # summary() line — publish into the process metrics registry
        try:
            from ..observability import get_registry

            get_registry().counter(
                "paddle_analysis_guard_fires_total",
                help="trace-guard findings (recompile storms), by rule "
                     "and watched graph",
            ).inc(rule=f.rule, graph=str(key))
            from ..observability import get_flight_recorder

            get_flight_recorder().note(
                "guard_fire", rule=f.rule, graph=str(key), detail=detail,
            )
        except Exception:
            pass
        for cb in list(self._callbacks):
            try:
                cb(f)
            except Exception:
                pass
        return f

    def compile_counts(self):
        with self._lock:
            counts = {k: len(v) for k, v in self._sigs.items()}
            for name, slot in self._watched.items():
                counts[name] = max(
                    counts.get(name, 0),
                    self._cache_size(slot[0]) - slot[1],
                )
        return counts

    def reset(self):
        with self._lock:
            self._sigs.clear()
            self._fired.clear()
            self.findings.clear()
            for slot in self._watched.values():
                slot[1] = self._cache_size(slot[0])  # re-baseline


# One process-wide guard: compile storms are a process property. Swap a
# fresh guard in for tests via ``use_guard``.
_GUARD = TraceGuard()


def get_guard() -> TraceGuard:
    return _GUARD


def record_compile(key, signature, origin=""):
    return _GUARD.record_compile(key, signature, origin)


class use_guard:
    """Context manager installing a replacement guard (tests)."""

    def __init__(self, guard):
        self.guard = guard
        self._prev = None

    def __enter__(self):
        global _GUARD
        self._prev, _GUARD = _GUARD, self.guard
        return self.guard

    def __exit__(self, *exc):
        global _GUARD
        _GUARD = self._prev
        return False


# ---------------------------------------------------------------- tracers
def find_leaked_tracers(obj, _path="", _out=None, _seen=None):
    """Walk a pytree / Layer / dict for jax Tracer instances. Returns
    ``[(path, tracer), ...]`` — non-empty means a trace escaped its jit
    (a later use will raise ``UnexpectedTracerError`` at a distance)."""
    out = [] if _out is None else _out
    seen = set() if _seen is None else _seen
    if id(obj) in seen:
        return out
    seen.add(id(obj))
    Tracer = jax.core.Tracer
    if isinstance(obj, Tracer):
        out.append((_path or "<root>", obj))
        return out
    # paddle Layer: parameters + buffers are where tracers leak
    if hasattr(obj, "named_parameters") and hasattr(obj, "named_buffers"):
        for k, p in obj.named_parameters():
            find_leaked_tracers(
                getattr(p, "value", p), f"{_path}params.{k}", out, seen
            )
        for k, b in obj.named_buffers():
            find_leaked_tracers(
                getattr(b, "value", b), f"{_path}buffers.{k}", out, seen
            )
        return out
    if hasattr(obj, "value") and not isinstance(obj, (dict, list, tuple)):
        find_leaked_tracers(obj.value, f"{_path}.value", out, seen)
        return out
    if isinstance(obj, dict):
        for k, v in obj.items():
            find_leaked_tracers(v, f"{_path}[{k!r}]", out, seen)
        return out
    if isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            find_leaked_tracers(v, f"{_path}[{i}]", out, seen)
        return out
    return out


def lint_leaked_tracers(obj, graph=""):
    """Finding-producing wrapper over :func:`find_leaked_tracers`."""
    from .findings import Report

    rep = Report()
    for path, _tr in find_leaked_tracers(obj):
        rep.add(Finding(
            rule="leaked-tracer", severity=Severity.ERROR,
            message=(
                f"tracer leaked into {path} — a jit trace escaped; "
                f"restore concrete state after tracing (write-back "
                f"pattern)"
            ),
            graph=graph, detail=path,
        ))
    return rep
