"""Donation-aware live-range HBM footprint analysis over closed jaxprs.

Every memory claim this repo makes (the 7B per-chip budget table, the
paged-vs-slab concurrency wins, int8 KV capacity) was hand-analytic
until now; this pass derives a peak-resident-bytes figure from the
*program itself*, so an OOM in a decode step, a prefill bucket or a
speculative verify program is discoverable before any chip time is
burned.

The model walks a closed jaxpr's eqns as a timeline:

- undonated inputs and captured consts are resident for the whole
  program (XLA holds the caller's buffers alive);
- a DONATED input dies at its last use, and when that last use produces
  an output of the same shape/dtype the buffer is reused in place (the
  aliasing XLA actually performs) — the donation credit;
- an intermediate lives from its defining eqn to its last use; program
  outputs live from their defining eqn to the end;
- while an eqn executes, its outputs coexist with its operands, and a
  structured-control-flow eqn (scan/while/cond/pjit/shard_map) adds its
  sub-jaxpr's own internal transient peak (one loop iteration's
  internals — XLA reuses the body buffers across trips).

``peak_bytes`` is the max over that timeline. It deliberately ignores
fusion (XLA fuses elementwise chains into zero materialized
intermediates), so it is an *upper-bound-shaped estimate*, validated
against ``compiled.memory_analysis()`` where the installed jax exposes
it (:func:`xla_memory_stats` / :func:`drift_finding` — drift beyond the
gate is a counted finding, not a silent miss).

Per-chip figures use ``sharding.shard_shape`` on any leaf that carries
a sharding (:func:`per_chip_bytes` — the ``lower_7b.measured_per_chip``
discipline, generalized): intermediates without sharding metadata are
counted full-size, so the per-chip peak is exact for the
state-dominated programs it gates (the 7B layouts) and conservative
elsewhere.

Rules (ratcheted through the same baseline as every other lint):

- ``hbm-budget-exceeded``  estimated peak above the device-kind budget
                           table (or an explicit ``budget_bytes=``).
- ``peak-doubling``        the whole-program peak holds >= 2x the
                           program's own argument bytes — the
                           missed-donation / extra-copy shape (a train
                           or optimizer step that double-buffers its
                           state).
- ``transient-blowup``     one eqn materializes a single output above a
                           configurable fraction of budget (the
                           attention-matrix / one-hot blowup shape).
"""
from __future__ import annotations

import dataclasses

import numpy as np

import jax

from .findings import Finding, Report, Severity
from .jaxpr_lint import (
    ClosedJaxpr,
    Var,
    _aval_str,
    _donated_flags,
    _nbytes,
    _src,
    _sub_jaxprs,
)

_GIB = 1 << 30

#: device_kind (``jax.devices()[0].device_kind``) prefix -> HBM bytes.
#: Matched longest-prefix-first, case-insensitive. The cpu row is a
#: stand-in budget so dogfooding on the CPU backend exercises the same
#: rule path (host RAM class, not a chip claim).
DEVICE_HBM_BUDGETS = {
    "TPU v3": 16 * _GIB,
    "TPU v4": 32 * _GIB,
    "TPU v5 lite": 16 * _GIB,
    "TPU v5e": 16 * _GIB,
    "TPU v5p": 95 * _GIB,
    "TPU v5": 95 * _GIB,
    "TPU v6 lite": 32 * _GIB,
    "TPU v6e": 32 * _GIB,
    "cpu": 64 * _GIB,
}


def budget_for_device_kind(kind):
    """HBM budget for a device-kind string (longest matching prefix of
    :data:`DEVICE_HBM_BUDGETS`), or None when the kind is unknown."""
    if not kind:
        return None
    k = str(kind).lower()
    best = None
    for prefix, bytes_ in DEVICE_HBM_BUDGETS.items():
        if k.startswith(prefix.lower()):
            if best is None or len(prefix) > best[0]:
                best = (len(prefix), bytes_)
    return None if best is None else best[1]


@dataclasses.dataclass
class MemoryConfig:
    """Budgets and rule thresholds. Tests shrink them to force
    firings; the CLI uses the defaults against the device table."""

    #: explicit budget; None -> look up ``device_kind`` in the table
    budget_bytes: int | None = None
    #: None -> ``jax.devices()[0].device_kind``
    device_kind: str | None = None
    #: fraction of the budget a program may use before the budget rule
    #: fires (headroom for the allocator, infeed, and the runtime)
    budget_fraction: float = 0.9
    peak_doubling_ratio: float = 2.0
    #: floor below which peak-doubling stays silent (tiny test graphs
    #: double constantly and harmlessly)
    min_peak_doubling_bytes: int = 64 << 20
    #: single-output transient threshold, as a fraction of budget
    transient_fraction: float = 0.5
    min_transient_bytes: int = 64 << 20

    def resolved_budget(self):
        if self.budget_bytes is not None:
            return int(self.budget_bytes)
        kind = self.device_kind
        if kind is None:
            try:
                kind = jax.devices()[0].device_kind
            except Exception:
                return None
        return budget_for_device_kind(kind)


def per_chip_bytes(x):
    """Bytes of one shard of ``x`` (an aval, jax.Array or
    ShapeDtypeStruct): ``sharding.shard_shape`` when a sharding is
    attached, full size otherwise — the ``lower_7b`` measurement
    discipline as a reusable primitive."""
    shape = tuple(getattr(x, "shape", ()) or ())
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        return 0
    sh = getattr(x, "sharding", None)
    if sh is not None and hasattr(sh, "shard_shape"):
        try:
            shape = tuple(sh.shard_shape(shape))
        except Exception:
            pass
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


@dataclasses.dataclass
class MemoryEstimate:
    """One program's footprint: the whole-program byte classes plus the
    timeline peak and its provenance."""

    graph: str
    args_bytes: int
    donated_bytes: int
    consts_bytes: int
    outputs_bytes: int
    peak_bytes: int
    peak_where: str          # eqn provenance at the peak instant
    max_single_bytes: int    # largest single eqn output anywhere
    max_single_aval: str
    max_single_where: str
    n_eqns: int
    #: args bytes with sharded leaves scaled by shard_shape (equals
    #: args_bytes when no input carries a sharding)
    per_chip_args_bytes: int

    @property
    def per_chip_peak_bytes(self):
        """Peak with the args' sharding applied; intermediates carry no
        sharding metadata and stay full-size (exact for
        state-dominated programs, conservative elsewhere)."""
        return self.peak_bytes - self.args_bytes + self.per_chip_args_bytes

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["per_chip_peak_bytes"] = self.per_chip_peak_bytes
        d["peak_gib"] = round(self.peak_bytes / _GIB, 4)
        d["per_chip_peak_gib"] = round(self.per_chip_peak_bytes / _GIB, 4)
        return d


def _is_var(v):
    return isinstance(v, Var)


# ------------------------------------------------------- fusion discount
# XLA loop-fuses an elementwise producer into its single consumer (the
# whole adam update chain is ONE kernel with zero materialized
# intermediates); counting every chain link would overestimate
# elementwise-heavy programs ~2x (measured on the dogfood optimizer
# step). An elementwise output with exactly one fusible consumer — and
# any pure aliasing op's output — is therefore not charged a buffer.
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "neg", "sign", "abs", "max", "min",
    "pow", "integer_pow", "sqrt", "rsqrt", "exp", "log", "log1p",
    "expm1", "tanh", "logistic", "erf", "erfc", "erf_inv", "sin",
    "cos", "floor", "ceil", "round", "clamp", "select_n", "rem",
    "and", "or", "xor", "not", "eq", "ne", "ge", "gt", "le", "lt",
    "convert_element_type", "is_finite", "nextafter", "square",
    "cbrt", "atan2", "real", "imag",
}
#: consumers an elementwise producer fuses INTO (elementwise chains,
#: reductions, shape ops). A dot/conv/scatter consumer reads a
#: materialized operand — no discount.
_FUSIBLE_CONSUMERS = _ELEMENTWISE | {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin",
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims",
    "transpose", "slice", "rev",
}
#: pure metadata ops: the output aliases the operand's buffer
_ALIAS_PRIMS = {"reshape", "squeeze", "expand_dims",
                "bitcast_convert_type"}


def _consumer_prims(jaxpr):
    """Var -> list of consuming primitive names at this jaxpr level
    (program outvars additionally count as a 'return' consumer)."""
    cons = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if _is_var(v):
                cons.setdefault(v, []).append(eqn.primitive.name)
    for v in jaxpr.outvars:
        if _is_var(v):
            cons.setdefault(v, []).append("return")
    return cons


def _fused_away(eqn, v, consumers):
    """True when ``v`` (an output of ``eqn``) never owns a buffer."""
    prim = eqn.primitive.name
    c = consumers.get(v, ())
    if prim in _ALIAS_PRIMS:
        # a program output must own its buffer (its aliased operand is
        # freed at the alias point; the result is returned)
        return "return" not in c
    if prim not in _ELEMENTWISE:
        return False
    # XLA duplicates a cheap elementwise producer into EVERY fusible
    # consumer (no buffer even with fan-out); one non-fusible consumer
    # (dot/conv/scatter) forces materialization
    return bool(c) and all(p in _FUSIBLE_CONSUMERS for p in c)


def _transient_peak(jaxpr):
    """Internal liveness peak of a sub-jaxpr: consts + intermediates
    over its own timeline. Its invars are bound to outer buffers (the
    outer walk already counts them) and its outvars alias the outer
    eqn's outputs, so neither is pinned here — this is the *extra*
    memory one trip through the body holds."""
    live = {}
    for cv in jaxpr.constvars:
        live[cv] = _nbytes(cv.aval)
    last = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last[v] = i
    consumers = _consumer_prims(jaxpr)
    live_bytes = sum(live.values())
    peak = live_bytes
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(
            _nbytes(v.aval) for v in eqn.outvars
            if not _fused_away(eqn, v, consumers)
        )
        sub_t = max(
            (_transient_peak(s) for s in _sub_jaxprs(eqn)), default=0
        )
        peak = max(peak, live_bytes + out_b + sub_t)
        for v in eqn.outvars:
            if _is_var(v) and last.get(v, -1) > i \
                    and not _fused_away(eqn, v, consumers):
                live[v] = _nbytes(v.aval)
                live_bytes += live[v]
        for v in {v for v in eqn.invars if _is_var(v)}:
            if last.get(v) == i and v in live:
                live_bytes -= live.pop(v)
    return peak


def estimate_closed(closed, *, graph="", donated=None, arg_shardings=None,
                    config=None):
    """Walk one closed jaxpr and return a :class:`MemoryEstimate`.

    ``donated``: per-invar bools (the production call site's
    ``donate_argnums``, flattened — ``jaxpr_lint._donated_flags``).
    ``arg_shardings``: optional per-invar sharding objects for the
    per-chip figures (traced avals on this jax don't carry shardings,
    so the example args' must be passed alongside).
    """
    del config  # config gates the rules, not the estimate
    jaxpr = closed.jaxpr if isinstance(closed, ClosedJaxpr) else closed
    invars, n = jaxpr.invars, len(jaxpr.eqns)
    donated = list(donated) if donated is not None else []
    donated += [False] * (len(invars) - len(donated))
    shardings = list(arg_shardings) if arg_shardings is not None else []
    shardings += [None] * (len(invars) - len(shardings))

    args_bytes = sum(_nbytes(v.aval) for v in invars)
    donated_bytes = sum(
        _nbytes(v.aval) for v, d in zip(invars, donated) if d
    )
    consts_bytes = sum(_nbytes(v.aval) for v in jaxpr.constvars)
    outputs_bytes = sum(
        _nbytes(getattr(v, "aval", None)) if hasattr(v, "aval") else 0
        for v in jaxpr.outvars
    )
    per_chip_args = 0
    for v, sh in zip(invars, shardings):
        if sh is not None and hasattr(sh, "shard_shape"):
            try:
                shard = tuple(sh.shard_shape(tuple(v.aval.shape)))
                per_chip_args += int(
                    np.prod(shard, dtype=np.int64)
                ) * np.dtype(v.aval.dtype).itemsize
                continue
            except Exception:
                pass
        per_chip_args += _nbytes(v.aval)

    # ---- liveness ----------------------------------------------------
    last = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last[v] = i
    for v in jaxpr.outvars:
        if _is_var(v):
            last[v] = n  # program outputs live to the end
    # donation pairing: a donated input whose shape/dtype matches a
    # program output is aliased in place by XLA (the output IS the
    # donated buffer, written through the whole program) — pin the
    # input to program end and never charge the paired output.
    # Donated-but-unmatched inputs die at their last use instead.
    out_slots = {}
    for v in jaxpr.outvars:
        if _is_var(v):
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "shape", None) \
                    is not None:
                k = (tuple(aval.shape), np.dtype(aval.dtype).name)
                out_slots.setdefault(k, []).append(v)
    paired_out = set()
    live = {cv: _nbytes(cv.aval) for cv in jaxpr.constvars}
    for v, d in zip(invars, donated):
        live[v] = _nbytes(v.aval)
        if not d:
            last[v] = n  # undonated: resident whole program
            continue
        k = (tuple(v.aval.shape), np.dtype(v.aval.dtype).name)
        slots = out_slots.get(k)
        if slots:
            w = slots.pop()
            if w is not v:
                paired_out.add(w)
            last[v] = n  # the buffer lives on as the output
        # else: donated and consumed — dies at its natural last use

    consumers = _consumer_prims(jaxpr)
    live_bytes = sum(live.values())
    peak, peak_where = live_bytes, "entry"
    max_single, max_single_aval, max_single_where = 0, "", ""

    def _scan_single(jx):
        """Largest single eqn output at any depth (transient rule)."""
        nonlocal max_single, max_single_aval, max_single_where
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                nb = _nbytes(getattr(ov, "aval", None)) if hasattr(
                    ov, "aval") else 0
                if nb > max_single:
                    max_single = nb
                    max_single_aval = _aval_str(ov.aval)
                    max_single_where = _src(eqn) or eqn.primitive.name
            for sub in _sub_jaxprs(eqn):
                _scan_single(sub)

    _scan_single(jaxpr)

    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(
            _nbytes(v.aval) for v in eqn.outvars
            if v not in paired_out
            and not _fused_away(eqn, v, consumers)
        )
        sub_t = max(
            (_transient_peak(s) for s in _sub_jaxprs(eqn)), default=0
        )
        during = live_bytes + out_b + sub_t
        if during > peak:
            peak = during
            peak_where = _src(eqn) or eqn.primitive.name
        for v in eqn.outvars:
            if _is_var(v) and last.get(v, -1) > i and v not in live \
                    and v not in paired_out \
                    and not _fused_away(eqn, v, consumers):
                live[v] = _nbytes(v.aval)
                live_bytes += live[v]
        for v in {v for v in eqn.invars if _is_var(v)}:
            if last.get(v) == i and v in live:
                live_bytes -= live.pop(v)
    peak = max(peak, live_bytes)

    return MemoryEstimate(
        graph=graph, args_bytes=args_bytes, donated_bytes=donated_bytes,
        consts_bytes=consts_bytes, outputs_bytes=outputs_bytes,
        peak_bytes=peak, peak_where=peak_where,
        max_single_bytes=max_single, max_single_aval=max_single_aval,
        max_single_where=max_single_where, n_eqns=n,
        per_chip_args_bytes=per_chip_args,
    )


def lint_estimate(est, *, config=None):
    """The three ratcheted rules over one :class:`MemoryEstimate`."""
    cfg = config or MemoryConfig()
    rep = Report()
    budget = cfg.resolved_budget()
    usable = None if budget is None else int(budget * cfg.budget_fraction)
    if usable is not None and est.peak_bytes > usable:
        rep.add(Finding(
            rule="hbm-budget-exceeded", severity=Severity.ERROR,
            message=(
                f"estimated peak {est.peak_bytes / _GIB:.2f} GiB exceeds "
                f"{cfg.budget_fraction:.0%} of the "
                f"{budget / _GIB:.0f} GiB device budget "
                f"(peak at {est.peak_where or 'entry'})"
            ),
            graph=est.graph, where=est.peak_where,
            detail=f"budget:{budget >> 30}GiB",
        ))
    base = est.args_bytes + est.consts_bytes
    if (
        base >= cfg.min_peak_doubling_bytes
        and est.peak_bytes >= cfg.peak_doubling_ratio * base
    ):
        rep.add(Finding(
            rule="peak-doubling", severity=Severity.WARNING,
            message=(
                f"peak {est.peak_bytes / _GIB:.2f} GiB is "
                f"{est.peak_bytes / max(base, 1):.1f}x the program's own "
                f"{base / _GIB:.2f} GiB of arguments — the missed-"
                f"donation / extra-copy shape (donate the state or drop "
                f"the copy; peak at {est.peak_where or 'entry'})"
            ),
            graph=est.graph, where=est.peak_where,
            detail=f"ratio>={cfg.peak_doubling_ratio:g}",
        ))
    if (
        usable is not None
        and est.max_single_bytes >= cfg.min_transient_bytes
        and est.max_single_bytes >= cfg.transient_fraction * budget
    ):
        rep.add(Finding(
            rule="transient-blowup", severity=Severity.WARNING,
            message=(
                f"one eqn materializes {est.max_single_aval} "
                f"({est.max_single_bytes / _GIB:.2f} GiB, "
                f">{cfg.transient_fraction:.0%} of the "
                f"{budget / _GIB:.0f} GiB budget) at "
                f"{est.max_single_where}"
            ),
            graph=est.graph, where=est.max_single_where,
            detail=f"single:{est.max_single_aval}",
        ))
    return rep


def lint_memory_closed(closed, *, graph="", donated=None,
                       arg_shardings=None, config=None):
    """Estimate + rules in one call (what tpu_lint's --memory runs)."""
    est = estimate_closed(
        closed, graph=graph, donated=donated, arg_shardings=arg_shardings,
    )
    return lint_estimate(est, config=config), est


def _leaf_shardings(args, static_argnums=()):
    static = set(static_argnums or ())
    out = []
    for i, a in enumerate(args):
        if i in static:
            continue
        for leaf in jax.tree_util.tree_leaves(a):
            out.append(getattr(leaf, "sharding", None))
    return out


def estimate_fn(fn, *args, graph="", donate_argnums=(), static_argnums=(),
                **kwargs):
    """Trace ``fn`` with example args and estimate the footprint,
    reading donation from the *production* call site's
    ``donate_argnums`` and per-chip sharding from the example leaves."""
    closed = jax.make_jaxpr(fn, static_argnums=tuple(static_argnums))(
        *args, **kwargs
    )
    donated = _donated_flags(args, donate_argnums, static_argnums)
    shardings = _leaf_shardings(args, static_argnums)
    for v in kwargs.values():
        leaves = jax.tree_util.tree_leaves(v)
        donated += [False] * len(leaves)
        shardings += [getattr(x, "sharding", None) for x in leaves]
    return estimate_closed(
        closed, graph=graph or getattr(fn, "__name__", "fn"),
        donated=donated, arg_shardings=shardings,
    )


def lint_memory_fn(fn, *args, graph="", donate_argnums=(),
                   static_argnums=(), config=None, **kwargs):
    est = estimate_fn(
        fn, *args, graph=graph, donate_argnums=donate_argnums,
        static_argnums=static_argnums, **kwargs
    )
    return lint_estimate(est, config=config), est


# ---------------------------------------------------------------- XLA gate
def xla_memory_stats(compiled):
    """``compiled.memory_analysis()`` as a plain dict with a derived
    ``peak_bytes`` (args + outputs + temps - donation aliases), or None
    when the installed jax/backend doesn't expose it."""
    try:
        ma = compiled.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
    except Exception:
        return None
    return {
        "argument_size_in_bytes": arg,
        "output_size_in_bytes": out,
        "temp_size_in_bytes": tmp,
        "alias_size_in_bytes": alias,
        "peak_bytes": arg + out + tmp - alias,
    }


def drift_finding(est, stats, *, tolerance=0.2, slack_bytes=1 << 20):
    """Validate the estimator against XLA's own accounting: None when
    ``est.peak_bytes`` is within ``tolerance`` (plus an absolute slack
    floor for tiny programs) of the XLA-derived peak, else a counted
    ``memory-analysis-drift`` finding. The estimator ignores fusion so
    it sits ABOVE the XLA figure; the gate bounds both directions —
    an underestimate is the dangerous one."""
    xp = int(stats["peak_bytes"])
    allowed = max(tolerance * xp, slack_bytes)
    dev = est.peak_bytes - xp
    if abs(dev) <= allowed:
        return None
    return Finding(
        rule="memory-analysis-drift", severity=Severity.WARNING,
        message=(
            f"estimated peak {est.peak_bytes} B vs XLA "
            f"memory_analysis {xp} B "
            f"({'+' if dev >= 0 else ''}{dev / max(xp, 1):.0%}, gate "
            f"±{tolerance:.0%}) — the live-range model drifted from "
            f"the compiler; re-derive before trusting the budget table"
        ),
        graph=est.graph,
        detail=f"drift:{'over' if dev > 0 else 'under'}",
    )
