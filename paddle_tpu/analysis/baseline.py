"""Baseline: the checked-in set of accepted findings.

The lint gate is *ratchet-shaped*: the repo's current graphs produce a
known finding set (each entry carries a ``why`` documenting the
decision to accept it — or the fix that removed it); CI fails only on
findings NOT in the baseline, so new hazards can't land while accepted
ones don't nag. Regenerate after an intentional change with
``python tools/tpu_lint.py --update-baseline``.

Matching is by :meth:`Finding.key` (rule|graph|detail) — deliberately
free of line numbers and message text, so refactors that move code or
reword messages don't invalidate the baseline.
"""
from __future__ import annotations

import json
import os

from .findings import Report


def load_baseline(path):
    """-> (set of accepted keys, full entry list). Missing file = empty."""
    if not os.path.exists(path):
        return set(), []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", [])
    # "fixed|..." keys are documentation of hazards already fixed — they
    # can never match a live finding and must not count as stale
    keys = {e["key"] for e in entries
            if "key" in e and not e["key"].startswith("fixed|")}
    return keys, entries


def save_baseline(path, report, notes=None, extra_entries=None):
    """Write the baseline for ``report``. ``notes`` maps finding key ->
    'why accepted' text; unnoted entries get a placeholder so review
    can spot them. ``extra_entries`` are preserved verbatim (e.g.
    documented fixed-findings history)."""
    notes = notes or {}
    seen = set()
    entries = []
    for f in report.sorted():
        k = f.key()
        if k in seen:
            continue
        seen.add(k)
        entries.append({
            "key": k,
            "rule": f.rule,
            "severity": f.severity,
            "graph": f.graph,
            "message": f.message,
            "why": notes.get(k, "accepted at baseline generation; "
                                "document or fix"),
        })
    for e in extra_entries or []:
        if e.get("key") not in seen:
            entries.append(e)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"format": "tpu_lint.baseline.v1", "findings": entries},
                  f, indent=1, sort_keys=False)
        f.write("\n")
    return entries


def diff_against_baseline(report, baseline_keys):
    """-> (new Report of unaccepted findings, stale keys no longer
    produced). Stale keys are informational — they mean a documented
    hazard got fixed and the baseline can be regenerated smaller."""
    new = Report()
    produced = set()
    for f in report:
        k = f.key()
        produced.add(k)
        if k not in baseline_keys:
            new.add(f)
    stale = sorted(baseline_keys - produced)
    return new, stale


def assert_no_new_findings(report, baseline_path):
    """Raise AssertionError listing any finding not in the baseline —
    the pytest-facing entry point."""
    keys, _ = load_baseline(baseline_path)
    new, _stale = diff_against_baseline(report, keys)
    if len(new):
        lines = "\n".join(f"  {f}" for f in new.sorted())
        raise AssertionError(
            f"{len(new)} lint finding(s) not in baseline "
            f"{baseline_path}:\n{lines}\n"
            f"Fix them, suppress inline (# tpu-lint: disable=<rule>), or "
            f"regenerate: python tools/tpu_lint.py --update-baseline"
        )
