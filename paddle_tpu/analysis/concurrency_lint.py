"""Host-concurrency lint: lock discipline over the threaded runtimes.

The serving fleet, checkpoint writer, resilience watchdog, and
observability registry are all lock-per-class threaded code — and every
review since PR 5 has hand-checked the same three properties. This pass
checks them statically, per class:

- ``lock-order-inversion``  builds the class's lock-ACQUISITION-ORDER
  graph from ``with self._lock:`` nesting (plus statement-level
  ``.acquire()``/``.release()`` pairs) with ONE level of call-graph
  interprocedural propagation: holding A while calling a method that
  acquires B adds the A->B edge too. A cycle in that graph is a
  deadlock waiting for the right interleaving; a nested re-acquisition
  of a known non-reentrant ``threading.Lock`` is a deadlock on the
  spot (``self:`` detail).
- ``unlocked-shared-write``  an attribute the class writes BOTH under a
  lock and outside one (outside ``__init__``) — the lock is evidently
  meant to protect it, and the unlocked write is the torn-state race.
  A second trigger (``:thread`` detail): in a lock-holding class, an
  unlocked ``self.X`` write inside a method reachable from a
  ``threading.Thread`` target — a background thread publishing state
  the rest of the class reads (the fleet-router health-map shape).
- ``blocking-call-under-lock``  a known-blocking call (``join()``,
  ``.result()``, socket/HTTP I/O, ``time.sleep``, subprocess waits)
  while a lock is held — every other thread touching that lock now
  waits on the network too. One level interprocedural: holding a lock
  while calling a method whose body blocks fires the same rule.
  ``Condition.wait()`` on a class Condition attribute is exempt (it
  RELEASES the lock while waiting — that is the point of a condition).

``threading.Condition(self._lock)`` attributes alias the wrapped lock:
``with self._cond:`` acquires the same underlying lock, and the order
graph treats them as one node.

Findings key on class/attr/method names (never line numbers), so the
baseline survives refactors that move code. Suppress inline with
``# tpu-lint: disable=<rule>``. The runtime counterpart — the lock
sentinel that catches ACTUAL inversions under the chaos harnesses —
lives in :mod:`lock_sentinel`.
"""
from __future__ import annotations

import ast

from .ast_lint import _dotted, suppressed as _suppressed
from .findings import Finding, Report, Severity

# attribute calls that block the calling thread
_BLOCKING_ATTRS = {
    "join", "result", "sleep", "recv", "recv_into", "accept",
    "connect", "sendall", "getresponse", "request", "urlopen",
    "readline",
}
# fully-dotted callables that block
_BLOCKING_DOTTED = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "urllib.request.urlopen",
}
_THREAD_REACH_DEPTH = 3

# container mutations that count as writes to ``self.X``
_MUTATOR_ATTRS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "add", "discard", "remove", "appendleft",
}


def _self_attr(node):
    """'X' when ``node`` is ``self.X``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassScan:
    """Everything the three rules need to know about one class."""

    def __init__(self, cls, rel, lines):
        self.cls = cls
        self.rel = rel
        self.lines = lines
        self.name = cls.name
        self.lock_attrs = set()       # ctor-confirmed Lock() attrs
        self.rlock_attrs = set()      # ctor-confirmed RLock() attrs
        self.assumed_lock_attrs = set()  # name-based `with self.X:` only
        self.cond_attrs = {}          # Condition attr -> wrapped lock | None
        self.event_attrs = set()
        self.methods = {}             # name -> FunctionDef
        self.thread_targets = set()   # method names run on threads
        self._method_calls = {}       # name -> set of self-method names
        self.direct_acquires = {}     # name -> set of lock ids
        self.direct_blocking = {}     # name -> [callname]
        self.edges = {}               # (a, b) -> (method, lineno)
        self.self_cycles = {}         # lock -> (method, lineno)
        self.writes = []              # (attr, locked, method, lineno)
        self.blocking = []            # (callname, method, lineno)
        self._discover()

    # ---------------------------------------------------------- discovery
    def _discover(self):
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
        for m in self.methods.values():
            for n in ast.walk(m):
                if isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Call
                ):
                    self._scan_ctor_assign(n)
                if isinstance(n, ast.Call):
                    self._scan_thread(n)
        # lock names used only via `with self.X:` (lock passed in from
        # outside the class): name-based fallback. Kind unknown — it
        # could be an RLock, so the self-reacquire rule must give it
        # the benefit of the doubt (assumed set, not lock_attrs).
        for m in self.methods.values():
            for n in ast.walk(m):
                if isinstance(n, ast.With):
                    for item in n.items:
                        a = _self_attr(item.context_expr)
                        if a and "lock" in a.lower() and \
                                a not in self.cond_attrs and \
                                a not in self.lock_attrs and \
                                a not in self.rlock_attrs:
                            self.assumed_lock_attrs.add(a)
        # per-method call graph + direct acquire/blocking summaries
        for name, m in self.methods.items():
            calls, acquires, blocking = set(), set(), []
            for n in ast.walk(m):
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name
                ) and n.func.value.id == "self" and \
                        n.func.attr in self.methods:
                    calls.add(n.func.attr)
                lk = self._acquire_of(n)
                if lk:
                    acquires.add(lk)
                b = self._blocking_name(n)
                if b:
                    blocking.append(b)
            for n in ast.walk(m):
                if isinstance(n, ast.With):
                    for item in n.items:
                        lk = self._lock_of(item.context_expr)
                        if lk:
                            acquires.add(lk)
            self._method_calls[name] = calls
            self.direct_acquires[name] = acquires
            self.direct_blocking[name] = blocking

    def _scan_ctor_assign(self, assign):
        ctor = _dotted(assign.value.func)
        if ctor is None:
            return
        last = ctor.split(".")[-1]
        for tgt in assign.targets:
            a = _self_attr(tgt)
            if a is None:
                continue
            if last == "Lock":
                self.lock_attrs.add(a)
            elif last == "RLock":
                self.rlock_attrs.add(a)
            elif last == "Condition":
                wrapped = None
                if assign.value.args:
                    wrapped = _self_attr(assign.value.args[0])
                self.cond_attrs[a] = wrapped
            elif last == "Event":
                self.event_attrs.add(a)

    def _scan_thread(self, call):
        name = _dotted(call.func)
        if not name or name.split(".")[-1] != "Thread":
            return
        for kw in call.keywords:
            if kw.arg == "target":
                t = _self_attr(kw.value)
                if t:
                    self.thread_targets.add(t)

    # ----------------------------------------------------------- helpers
    def _all_locks(self):
        return (self.lock_attrs | self.rlock_attrs
                | self.assumed_lock_attrs | set(self.cond_attrs))

    def _lock_id(self, attr):
        """Canonical node: a Condition aliases its wrapped lock."""
        wrapped = self.cond_attrs.get(attr)
        return wrapped if wrapped else attr

    def _lock_of(self, expr):
        """Lock id when ``expr`` is ``self.X`` for a known lock/cond."""
        a = _self_attr(expr)
        if a and a in self._all_locks():
            return self._lock_id(a)
        return None

    def _acquire_of(self, call):
        """Lock id when ``call`` is ``self.X.acquire(...)``."""
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "acquire":
            return self._lock_of(call.func.value)
        return None

    def _release_of(self, call):
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "release":
            return self._lock_of(call.func.value)
        return None

    def _blocking_name(self, call):
        """The blocking call's display name, or None. ``wait()`` on a
        Condition attribute is exempt: it releases the lock."""
        dotted = _dotted(call.func)
        if dotted in _BLOCKING_DOTTED:
            return dotted
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr == "wait":
            recv = _self_attr(call.func.value)
            if recv is not None and recv in self.cond_attrs:
                return None  # Condition.wait releases the lock
            return "wait"
        if attr == "join":
            # thread.join blocks; os.path.join / ", ".join do not
            if dotted and ("path" in dotted or dotted.startswith("os.")):
                return None
            if isinstance(call.func.value, ast.Constant):
                return None
            return "join"
        if attr in _BLOCKING_ATTRS:
            return attr
        return None

    def thread_reachable(self):
        seen = set(self.thread_targets)
        frontier = set(seen)
        for _ in range(_THREAD_REACH_DEPTH):
            nxt = set()
            for m in frontier:
                nxt |= self._method_calls.get(m, set()) - seen
            if not nxt:
                break
            seen |= nxt
            frontier = nxt
        return seen

    # --------------------------------------------------------- the walk
    def scan_methods(self):
        for name, m in self.methods.items():
            self._walk_stmts(m.body, [], name)

    def _note_edge(self, held, new, method, lineno):
        for h in held:
            if h == new:
                # re-acquiring a non-reentrant Lock deadlocks outright;
                # RLocks and unknown kinds are given the benefit
                if new in self.lock_attrs and \
                        new not in self.rlock_attrs:
                    self.self_cycles.setdefault(new, (method, lineno))
                continue
            self.edges.setdefault((h, new), (method, lineno))

    def _walk_stmts(self, stmts, held, method):
        """Statement-list walk threading the held-lock stack through
        ``with`` blocks and acquire()/release() pairs."""
        held = list(held)
        for stmt in stmts:
            # statement-level acquire()/release()
            for call in self._calls_in_stmt_head(stmt):
                lk = self._acquire_of(call)
                if lk:
                    self._note_edge(held, lk, method, call.lineno)
                    held.append(lk)
                rl = self._release_of(call)
                if rl and rl in held:
                    held.remove(rl)
            if isinstance(stmt, ast.With):
                locks_here = []
                for item in stmt.items:
                    lk = self._lock_of(item.context_expr)
                    if lk:
                        self._note_edge(held, lk, method, stmt.lineno)
                        locks_here.append(lk)
                self._scan_exprs(stmt, held, method)
                self._walk_stmts(stmt.body, held + locks_here, method)
                continue
            self._scan_exprs(stmt, held, method)
            for body in self._sub_bodies(stmt):
                self._walk_stmts(body, held, method)

    @staticmethod
    def _sub_bodies(stmt):
        for field in ("body", "orelse", "finalbody"):
            b = getattr(stmt, field, None)
            if b:
                yield b
        for h in getattr(stmt, "handlers", ()):
            yield h.body

    @staticmethod
    def _calls_in_stmt_head(stmt):
        """Calls in the statement itself, not in nested suites."""
        if isinstance(stmt, (ast.If, ast.While)):
            roots = [stmt.test]
        elif isinstance(stmt, ast.For):
            roots = [stmt.iter]
        elif isinstance(stmt, (ast.Try, ast.With)):
            roots = [i.context_expr for i in getattr(stmt, "items", [])]
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            roots = []
        else:
            roots = [stmt]
        out = []
        for r in roots:
            for n in ast.walk(r):
                if isinstance(n, ast.Call):
                    out.append(n)
        return out

    def _scan_exprs(self, stmt, held, method):
        """Record writes + blocking calls for one statement's head —
        nested suites are walked with their own held stack."""
        # ---- attribute writes ----------------------------------------
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
            if attr is None and isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    a = _self_attr(elt)
                    if a is not None:
                        self.writes.append(
                            (a, bool(held), method, stmt.lineno)
                        )
                continue
            if attr is not None:
                self.writes.append(
                    (attr, bool(held), method, stmt.lineno)
                )
        # in-place container mutations: self.X.append(...) etc.
        for call in self._calls_in_stmt_head(stmt):
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in _MUTATOR_ATTRS:
                attr = _self_attr(call.func.value)
                if attr is not None:
                    self.writes.append(
                        (attr, bool(held), method, call.lineno)
                    )
        # ---- blocking calls + one-level interprocedural --------------
        if not held:
            return
        for call in self._calls_in_stmt_head(stmt):
            b = self._blocking_name(call)
            if b:
                self.blocking.append((b, method, call.lineno))
            # one level of call graph: self.m() while a lock is held
            if isinstance(call.func, ast.Attribute) and isinstance(
                call.func.value, ast.Name
            ) and call.func.value.id == "self":
                callee = call.func.attr
                if callee in self.methods:
                    for lk in self.direct_acquires.get(callee, ()):
                        self._note_edge(held, lk, method, call.lineno)
                    for b2 in self.direct_blocking.get(callee, ()):
                        self.blocking.append(
                            (f"{callee}()->{b2}", method, call.lineno)
                        )

    # ----------------------------------------------------------- reports
    def report_into(self, rep):
        self.scan_methods()
        self._report_inversions(rep)
        self._report_unlocked_writes(rep)
        self._report_blocking(rep)

    def _add(self, rep, rule, severity, message, lineno, detail):
        if _suppressed(self.lines, lineno, rule):
            return
        rep.add(Finding(
            rule=rule, severity=severity, message=message,
            graph=self.rel, where=f"{self.rel}:{lineno}", detail=detail,
        ))

    def _report_inversions(self, rep):
        for lock, (method, lineno) in sorted(self.self_cycles.items()):
            self._add(
                rep, "lock-order-inversion", Severity.ERROR,
                f"{self.name}.{method} re-acquires non-reentrant lock "
                f"`self.{lock}` while already holding it — this "
                f"deadlocks on the spot (use an RLock or drop the "
                f"nested acquisition)",
                lineno, f"{self.name}:self:{lock}",
            )
        # cycles among distinct locks: DFS over the edge graph
        graph = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(graph.get(node, ())):
                    if nxt == start and len(path) > 1:
                        cyc = tuple(sorted(path))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        method, lineno = self.edges[(path[-1], start)]
                        order = "->".join(path + [start])
                        self._add(
                            rep, "lock-order-inversion", Severity.ERROR,
                            f"{self.name} acquires its locks in "
                            f"conflicting orders ({order}) — two "
                            f"threads taking the two orders deadlock; "
                            f"pick one global order",
                            lineno,
                            f"{self.name}:cycle:{'|'.join(cyc)}",
                        )
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))

    def _report_unlocked_writes(self, rep):
        if not self._all_locks():
            return
        skip = self._all_locks() | self.event_attrs
        by_attr = {}
        for attr, locked, method, lineno in self.writes:
            if attr in skip:
                continue
            by_attr.setdefault(attr, []).append((locked, method, lineno))
        reachable = self.thread_reachable()
        for attr, ws in sorted(by_attr.items()):
            locked_ws = [w for w in ws if w[0]]
            unlocked_ws = [w for w in ws
                           if not w[0] and w[1] != "__init__"]
            if locked_ws and unlocked_ws:
                _, method, lineno = unlocked_ws[0]
                self._add(
                    rep, "unlocked-shared-write", Severity.WARNING,
                    f"{self.name}.{attr} is written under a lock in "
                    f"`{locked_ws[0][1]}` but without one in "
                    f"`{method}` — the unlocked write races every "
                    f"locked reader",
                    lineno, f"{self.name}.{attr}",
                )
                continue
            thread_ws = [w for w in unlocked_ws if w[1] in reachable]
            if thread_ws:
                _, method, lineno = thread_ws[0]
                self._add(
                    rep, "unlocked-shared-write", Severity.WARNING,
                    f"{self.name}.{attr} is written without a lock in "
                    f"`{method}`, which runs on a background thread "
                    f"(threading.Thread target reach) — readers on "
                    f"other threads see torn/stale state",
                    lineno, f"{self.name}.{attr}:thread",
                )

    def _report_blocking(self, rep):
        seen = set()
        for callname, method, lineno in self.blocking:
            key = f"{self.name}.{method}:{callname}"
            if key in seen:
                continue
            seen.add(key)
            self._add(
                rep, "blocking-call-under-lock", Severity.WARNING,
                f"{self.name}.{method} calls `{callname}` while "
                f"holding a lock — every thread contending that lock "
                f"now waits on the blocking call too; move the slow "
                f"work outside the critical section",
                lineno, key,
            )


def lint_parsed(tree, lines, rel):
    """The lock-discipline rules over an already-parsed module."""
    rep = Report()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _ClassScan(node, rel, lines).report_into(rep)
    return rep


def lint_source(source, rel="<string>"):
    """Run the lock-discipline rules over one source string."""
    from .ast_lint import _parse_or_report

    tree, lines, rep = _parse_or_report(source, rel)
    if tree is None:
        return rep
    rep.extend(lint_parsed(tree, lines, rel))
    return rep


def lint_file(path, root=None):
    from .ast_lint import lint_one_file

    return lint_one_file(lint_parsed, path, root=root)


def lint_path(path, root=None, skip_dirs=None):
    """Recursively run the lock-discipline rules under ``path``."""
    from .ast_lint import DEFAULT_SKIP_DIRS, lint_tree

    return lint_tree(lint_parsed, path, root=root,
                     skip_dirs=skip_dirs or DEFAULT_SKIP_DIRS)
