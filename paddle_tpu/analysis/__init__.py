"""paddle_tpu.analysis — TPU-graph linter + recompilation guard.

Static analysis turned inward: every hazard class this repo shipped —
trace-time crashes, silent dtype promotion on hot paths, cache-parity
splits, shape-bucket recompile storms — was only discoverable by
*running* the graph on (or near) the chip. This package catches them
offline, the way upstream gates kernels through compile-time checks:

- :mod:`jaxpr_lint` — walks closed jaxprs of any jitted function and
  reports findings with severity + source provenance (fp64 leaks,
  convert churn, host transfers, donation misses, collective/mesh
  mismatches, broadcast blowups).
- :mod:`trace_guard` — runtime guard counting compile-cache entries per
  function; flags recompilation storms (same fn, drifting shapes) and
  detects leaked tracers.
- :mod:`ast_lint` — source-level pass for tensor-dependent Python
  control flow, host syncs inside ``@jit`` regions, and missing
  ``static_argnums``.
- :mod:`collective_lint` — the distributed-hang shape:
  ``collective-divergence`` (cond/switch branches with different
  collective schedules, wired into the jaxpr walk) plus AST rules
  ``rank-conditional-collective`` and ``collective-off-main-thread``.
- :mod:`concurrency_lint` — host lock discipline per class:
  ``lock-order-inversion`` (acquisition-order cycles),
  ``unlocked-shared-write``, ``blocking-call-under-lock``.
- :mod:`lock_sentinel` — the runtime counterpart: instrumented locks
  (``instrument_locks`` / ``PADDLE_TPU_LOCK_SENTINEL=1``) that catch
  ACTUAL lock-order inversions and long holds under the chaos
  harnesses, publishing ``paddle_analysis_lock_*`` metrics.
- :mod:`memory_lint` — donation-aware live-range HBM footprint
  estimator over closed jaxprs (``hbm-budget-exceeded``,
  ``peak-doubling``, ``transient-blowup``), validated against
  ``compiled.memory_analysis()`` where the installed jax exposes it.
- :mod:`baseline` — the ratchet: CI fails only on findings not in the
  checked-in baseline (``tools/tpu_lint_baseline.json``).

CLI: ``python tools/tpu_lint.py`` runs all passes over the repo's own
llama forward/backward, serving decode-step, and optimizer-step graphs.
Suppress an AST finding inline with ``# tpu-lint: disable=<rule>``.
"""
from __future__ import annotations

from . import collective_lint, concurrency_lint, lock_sentinel
from .ast_lint import lint_file, lint_path, lint_source
from .baseline import (
    assert_no_new_findings,
    diff_against_baseline,
    load_baseline,
    save_baseline,
)
from .findings import Finding, Report, Severity
from .jaxpr_lint import (
    LintConfig,
    lint_closed_jaxpr,
    lint_fn,
    lint_jitted,
)
from .memory_lint import (
    DEVICE_HBM_BUDGETS,
    MemoryConfig,
    MemoryEstimate,
    budget_for_device_kind,
    drift_finding,
    estimate_closed,
    estimate_fn,
    lint_estimate,
    lint_memory_closed,
    lint_memory_fn,
    per_chip_bytes,
    xla_memory_stats,
)
from .lock_sentinel import (
    LockSentinel,
    SentinelLock,
    get_sentinel,
    instrument_locks,
    maybe_instrument,
    use_sentinel,
)
from .trace_guard import (
    TraceGuard,
    find_leaked_tracers,
    get_guard,
    lint_leaked_tracers,
    record_compile,
    use_guard,
)

__all__ = [
    "Finding", "Report", "Severity", "LintConfig",
    "lint_closed_jaxpr", "lint_fn", "lint_jitted",
    "MemoryConfig", "MemoryEstimate", "DEVICE_HBM_BUDGETS",
    "budget_for_device_kind", "per_chip_bytes", "estimate_closed",
    "estimate_fn", "lint_estimate", "lint_memory_closed",
    "lint_memory_fn", "xla_memory_stats", "drift_finding",
    "lint_source", "lint_file", "lint_path",
    "collective_lint", "concurrency_lint", "lock_sentinel",
    "TraceGuard", "get_guard", "use_guard", "record_compile",
    "find_leaked_tracers", "lint_leaked_tracers",
    "LockSentinel", "SentinelLock", "get_sentinel",
    "instrument_locks", "maybe_instrument", "use_sentinel",
    "load_baseline", "save_baseline", "diff_against_baseline",
    "assert_no_new_findings",
]
