"""paddle_tpu.native — C components of the runtime.

Reference parity: the reference implements its data-loader transport,
allocators, and executors in C++ (SURVEY.md §2.1/§2.4); on TPU the
compute-side native surface is XLA itself, so the native code that
remains useful host-side is the IO path. This package holds a C
shared-memory SPSC ring buffer (shm_ring.c) used by the multiprocess
DataLoader: forked workers write collated numpy batches into per-worker
rings; the parent maps the same segments and reads them as zero-copy
numpy views.

The extension is compiled on first use with the system C compiler into
``_shm_ring.so`` next to this file (no pip/setup step; the build is one
``cc -O2 -shared -fPIC`` invocation). If no compiler is available the
DataLoader falls back to its thread-pool path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_shm_ring.so")
_SRC = os.path.join(_HERE, "shm_ring.c")
_LOCK = threading.Lock()
_LIB = None
HDR_SIZE = 4096


def _compile():
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        try:
            # -lrt: shm_open/shm_unlink live in librt on glibc < 2.34;
            # linking it makes the .so self-contained (without it, CDLL
            # resolution depends on whether some earlier import happened
            # to pull librt into the global scope — nondeterministic)
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", _SO, _SRC, "-lrt"],
                capture_output=True, text=True, timeout=120,
            )
            if r.returncode == 0:
                return True
            r = subprocess.run(  # toolchains without librt (musl etc.)
                [cc, "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                capture_output=True, text=True, timeout=120,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def get_lib():
    """ctypes handle to the ring library, compiling it if needed.
    Returns None when no C toolchain is available (failure is cached —
    we don't re-spawn compilers every DataLoader epoch)."""
    global _LIB
    with _LOCK:
        if _LIB is False:
            return None
        if _LIB is not None:
            return _LIB
        if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            if not _compile():
                _LIB = False
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign-platform binary: rebuild once, else give up
            if not _compile():
                _LIB = False
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                _LIB = False
                return None
        lib.shm_ring_attach.restype = ctypes.c_void_p
        lib.shm_ring_attach.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.shm_ring_capacity.restype = ctypes.c_uint64
        lib.shm_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_ring_detach.argtypes = [ctypes.c_void_p]
        lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        lib.shm_ring_closed.restype = ctypes.c_int
        lib.shm_ring_closed.argtypes = [ctypes.c_void_p]
        lib.shm_ring_write.restype = ctypes.c_int
        lib.shm_ring_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int64,
        ]
        lib.shm_ring_next.restype = ctypes.c_int64
        lib.shm_ring_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        lib.shm_ring_advance.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


class ShmRing:
    """Python face of one SPSC ring (create in the parent, attach in the
    forked worker — the fork inherits nothing but the shm NAME, keeping
    the two mappings independent)."""

    def __init__(self, name, capacity=None, create=False):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("no C compiler available for shm_ring")
        self._lib = lib
        self.name = name.encode()
        self._owner = bool(create)
        base = lib.shm_ring_attach(
            self.name, int(capacity or 0), 1 if create else 0
        )
        if not base:
            raise OSError(f"shm_ring_attach({name!r}) failed")
        self._base = base
        self.capacity = lib.shm_ring_capacity(base)
        import mmap as _m  # noqa: F401  (documentation: base IS an mmap)

    # ------------------------------------------------------------ producer
    def write(self, buf, timeout_ms=-1):
        r = self._lib.shm_ring_write(
            self._base, bytes(buf) if not isinstance(buf, (bytes, bytearray))
            else buf, len(buf), timeout_ms,
        )
        if r == -2:
            raise BrokenPipeError("ring closed")
        if r == -1:
            raise TimeoutError("ring write timeout")
        if r == -3:
            raise ValueError(
                f"record of {len(buf)} bytes exceeds the per-record limit "
                f"of capacity/2 ({self.capacity // 2} of {self.capacity}); "
                "raise the FLAGS_dataloader_shm_mb env var (default 64) "
                "or shrink the batch"
            )

    # ------------------------------------------------------------ consumer
    def next_view(self, timeout_ms=-1):
        """-> memoryview over the next record's payload (zero-copy into
        the shared segment), or None when the ring is closed and drained.
        Call advance() when done with the view."""
        off = ctypes.c_uint64()
        n = self._lib.shm_ring_next(
            self._base, ctypes.byref(off), timeout_ms
        )
        if n == -2:
            return None
        if n == -1:
            raise TimeoutError("ring read timeout")
        return (ctypes.c_char * n).from_address(
            self._base + off.value
        )

    def advance(self):
        self._lib.shm_ring_advance(self._base)

    # ------------------------------------------------------------ lifecycle
    def close(self):
        self._lib.shm_ring_close(self._base)

    @property
    def closed(self):
        return bool(self._lib.shm_ring_closed(self._base))

    def detach(self):
        if self._base:
            self._lib.shm_ring_detach(self._base)
            self._base = None

    def unlink(self):
        self._lib.shm_ring_unlink(self.name)

    def __del__(self):
        try:
            self.detach()
            if self._owner:
                self.unlink()
        except Exception:
            pass
