/* SPSC shared-memory ring buffer for DataLoader worker transport.
 *
 * Reference parity: the shared-memory queue under the reference's
 * multiprocess DataLoader (paddle/fluid/operators/reader/ + the
 * core._shared_memory machinery — unverified, mount empty), rebuilt as a
 * minimal single-producer/single-consumer ring: one forked worker writes
 * collated batch records, the parent maps the same segment and reads them
 * zero-copy (numpy views over the mmap).
 *
 * Layout: [header page][data area of `capacity` bytes]. head/tail are
 * monotonic byte offsets (mod capacity gives the position); records are
 * [u64 len][payload] padded to 8 bytes and never wrap — a len of
 * UINT64_MAX is a skip marker sending the reader back to offset 0.
 * Synchronization: C11 atomics + sched_yield/usleep spinning (batch
 * granularity makes wakeup latency irrelevant).
 */
#include <fcntl.h>
#include <sched.h>
#include <stdatomic.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#define HDR_SIZE 4096
#define ALIGN8(x) (((x) + 7ull) & ~7ull)
#define SKIP UINT64_MAX

typedef struct {
    uint64_t capacity;
    _Atomic uint64_t head; /* producer-owned write offset (monotonic) */
    _Atomic uint64_t tail; /* consumer-owned read offset (monotonic) */
    _Atomic uint32_t closed;
} ring_hdr;

static ring_hdr *hdr(void *base) { return (ring_hdr *)base; }
static char *data(void *base) { return (char *)base + HDR_SIZE; }

/* returns mmap'd base or NULL; capacity used only when create != 0 */
void *shm_ring_attach(const char *name, uint64_t capacity, int create) {
    int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
    int fd = shm_open(name, flags, 0600);
    if (fd < 0) return NULL;
    uint64_t total;
    if (create) {
        total = HDR_SIZE + capacity;
        if (ftruncate(fd, (off_t)total) != 0) {
            close(fd);
            shm_unlink(name);
            return NULL;
        }
    } else {
        struct stat st;
        if (fstat(fd, &st) != 0) { close(fd); return NULL; }
        total = (uint64_t)st.st_size;
    }
    void *base = mmap(NULL, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                      fd, 0);
    close(fd);
    if (base == MAP_FAILED) return NULL;
    if (create) {
        memset(base, 0, HDR_SIZE);
        hdr(base)->capacity = capacity;
    }
    return base;
}

uint64_t shm_ring_capacity(void *base) { return hdr(base)->capacity; }

void shm_ring_detach(void *base) {
    munmap(base, HDR_SIZE + hdr(base)->capacity);
}

int shm_ring_unlink(const char *name) { return shm_unlink(name); }

void shm_ring_close(void *base) {
    atomic_store(&hdr(base)->closed, 1u);
}

int shm_ring_closed(void *base) {
    return (int)atomic_load(&hdr(base)->closed);
}

static void backoff(int *spins) {
    if (++(*spins) < 64) sched_yield();
    else usleep(200);
}

/* free contiguous bytes at the producer's current position */
static uint64_t contiguous_free(ring_hdr *h, uint64_t head, uint64_t tail,
                                uint64_t *pos_out) {
    uint64_t cap = h->capacity;
    uint64_t used = head - tail;
    uint64_t pos = head % cap;
    uint64_t until_end = cap - pos;
    uint64_t free_total = cap - used;
    *pos_out = pos;
    return until_end < free_total ? until_end : free_total;
}

/* 0 ok, -1 timeout, -2 closed, -3 record too large */
int shm_ring_write(void *base, const void *src, uint64_t len,
                   int64_t timeout_ms) {
    ring_hdr *h = hdr(base);
    uint64_t need = ALIGN8(8 + len);
    /* records between capacity/2 and capacity can deadlock: too big to
     * fit after a mid-buffer head AND too big to wrap while the unread
     * tail pins the front — reject them up front so the producer errors
     * instead of spinning forever */
    if (need + 8 >= h->capacity / 2) return -3;
    int spins = 0;
    int64_t waited_us = 0;
    for (;;) {
        if (atomic_load(&h->closed)) return -2;
        uint64_t head = atomic_load(&h->head);
        uint64_t tail = atomic_load(&h->tail);
        uint64_t pos;
        uint64_t cfree = contiguous_free(h, head, tail, &pos);
        uint64_t cap = h->capacity;
        uint64_t free_total = cap - (head - tail);
        if (cfree >= need) {
            char *p = data(base) + pos;
            memcpy(p, &len, 8);
            memcpy(p + 8, src, len);
            atomic_store(&h->head, head + need);
            return 0;
        }
        /* not enough contiguous room at the end: emit skip + wrap, but
         * only once the reader has left the front of the buffer */
        uint64_t until_end = cap - (head % cap);
        if (free_total >= until_end + need && until_end >= 8) {
            char *p = data(base) + (head % cap);
            uint64_t skip = SKIP;
            memcpy(p, &skip, 8);
            atomic_store(&h->head, head + until_end);
            continue;
        }
        backoff(&spins);
        waited_us += (spins < 64) ? 1 : 200;
        if (timeout_ms >= 0 && waited_us / 1000 > timeout_ms) return -1;
    }
}

/* >=0: length of the next record (its payload offset written to
 * *payload_off, relative to segment start); -1 timeout; -2 closed+empty */
int64_t shm_ring_next(void *base, uint64_t *payload_off,
                      int64_t timeout_ms) {
    ring_hdr *h = hdr(base);
    int spins = 0;
    int64_t waited_us = 0;
    for (;;) {
        uint64_t head = atomic_load(&h->head);
        uint64_t tail = atomic_load(&h->tail);
        if (head != tail) {
            uint64_t cap = h->capacity;
            uint64_t pos = tail % cap;
            uint64_t len;
            memcpy(&len, data(base) + pos, 8);
            if (len == SKIP) {
                atomic_store(&h->tail, tail + (cap - pos));
                continue;
            }
            *payload_off = HDR_SIZE + pos + 8;
            return (int64_t)len;
        }
        if (atomic_load(&h->closed)) return -2;
        backoff(&spins);
        waited_us += (spins < 64) ? 1 : 200;
        if (timeout_ms >= 0 && waited_us / 1000 > timeout_ms) return -1;
    }
}

/* consume the record previously returned by shm_ring_next */
void shm_ring_advance(void *base) {
    ring_hdr *h = hdr(base);
    uint64_t tail = atomic_load(&h->tail);
    uint64_t pos = tail % h->capacity;
    uint64_t len;
    memcpy(&len, data(base) + pos, 8);
    atomic_store(&h->tail, tail + ALIGN8(8 + len));
}
