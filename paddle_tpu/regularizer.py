"""Weight-decay regularizers (python/paddle/regularizer.py parity —
unverified, mount empty)."""
from __future__ import annotations


class WeightDecayRegularizer:
    pass


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self.coeff})"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self.coeff})"
