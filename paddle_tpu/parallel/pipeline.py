"""Compiled SPMD pipeline parallelism: microbatch schedule over the pp
mesh axis with ppermute activation rotation.

Reference parity: the 1F1B/GPipe schedules of
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py and the
p2p machinery of pp_utils/p2p_communication.py (unverified, mount empty) —
re-expressed the TPU way (SURVEY.md §7 hard part #2): stage weights are
STACKED with the leading dim sharded over ``pp`` (stage s's chunk lives on
pp rank s), and one jitted program runs the whole microbatch schedule:

  tick t: every stage applies its block-chunk to its current activation,
  then the activations rotate one stage forward via lax.ppermute. Stage 0
  injects microbatch t; the last stage's outputs are collected. XLA's
  autodiff reverses the schedule (reverse ppermutes) for the backward
  pass, yielding the pipelined backward wave of the reference's 1F1B
  without hand-written p2p.

The eager/API engine (fleet.meta_parallel.PipelineParallel) drives the
same schedule imperatively; this module is the compiled perf path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp



def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim
    (shard dim 0 over the pp axis when placing)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def pipeline_apply(block_fn, chunk_params, h_mb, axis_name="pp",
                   num_stages=None):
    """Run the microbatch pipeline INSIDE a shard_map over ``axis_name``.

    block_fn(one_block_params, x) -> x
    chunk_params: local slice, leaves [1, blocks_per_stage, ...] (the
        shard_map in_spec puts the stage dim first; squeezed here)
    h_mb: [M, ...microbatch...] activations entering stage 0 (replicated
        over the pp axis)
    Returns [M, ...] outputs of the LAST stage, replicated over pp.
    """
    S = num_stages
    M = h_mb.shape[0]
    s = jax.lax.axis_index(axis_name)
    chunk = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0), chunk_params)

    def chunk_apply(x):
        def body(h, blk):
            return block_fn(blk, h), None

        h, _ = jax.lax.scan(body, x, chunk)
        return h

    perm = [(i, i + 1) for i in range(S - 1)]

    def tick(recv, t):
        x0 = h_mb[jnp.minimum(t, M - 1)]
        x_in = jnp.where(s == 0, x0, recv)
        y = chunk_apply(x_in)
        send = jax.lax.ppermute(y, axis_name, perm) if perm else y
        return send, y

    _, ys = jax.lax.scan(
        tick, jnp.zeros(h_mb.shape[1:], h_mb.dtype),
        jnp.arange(M + S - 1),
    )
    outs = ys[S - 1 :]
    # only the last stage holds real outputs; raw psum replicates them.
    # NOTE: under unchecked shard_map, a replicated out_spec's transpose
    # hands each device ct/n — and psum's transpose (psum) sums those n
    # pieces back to the full ct, so the pair is exactly grad-correct.
    # (Do NOT swap in an identity-bwd allreduce here; that halves grads.)
    mask = (s == S - 1).astype(outs.dtype)
    return jax.lax.psum(outs * mask, axis_name)


def make_pipeline_fn(block_fn, num_stages, mesh, axis_name="pp",
                     extra_in_specs=None):
    """Build a jittable fn(stacked_params, h_mb) -> outs where
    stacked_params leaves are [num_stages, blocks_per_stage, ...] sharded
    over ``axis_name`` on dim 0, h_mb is [M, ...] (replicated over pp; may
    carry other-axis shardings via ``extra_in_specs``)."""
    from jax.sharding import PartitionSpec as P

    h_spec = extra_in_specs if extra_in_specs is not None else P()

    def fn(stacked_params, h_mb):
        body = lambda cp, h: pipeline_apply(
            block_fn, cp, h, axis_name=axis_name, num_stages=num_stages
        )
        spec_params = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(spec_params, h_spec),
            out_specs=h_spec,
            check_vma=False,
        )(stacked_params, h_mb)

    return fn
