"""Compiled SPMD pipeline parallelism: microbatch schedule over the pp
mesh axis with ppermute activation rotation.

Reference parity: the GPipe/1F1B/interleaved schedules of
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py and the
p2p machinery of pp_utils/p2p_communication.py (unverified, mount empty) —
re-expressed the TPU way (SURVEY.md §7 hard part #2): stage weights are
STACKED with the leading dim sharded over ``pp`` (stage s's chunk lives on
pp rank s), and ONE jitted program runs the whole microbatch schedule:

  tick t: every stage applies its current block-chunk to its current
  activation, then activations rotate one stage forward via lax.ppermute
  (the ring wraps, so multi-pass interleaved schedules need no extra
  plumbing). Stage 0 injects microbatches; the last stage's outputs are
  collected. XLA's autodiff reverses the schedule (reverse ppermutes) for
  the backward pass, yielding the pipelined backward wave of the
  reference's 1F1B without hand-written p2p.

Interleaved virtual pipeline (reference: num_virtual_pipeline_stages>1,
Megatron-style): with v virtual stages per device, device d owns model
chunks c=0..v-1 holding blocks of virtual stage j = c*S + d, and each
activation makes v passes around the ring. The schedule assigns device d
at tick t the work item derived from local time r = t - d:

    s = r // S ;  c = s % v ;  m = (s // v) * S + r % S

which is conflict-free (each device processes exactly one chunk per tick),
dependency-exact (the ppermute ring delivers the wrapped activation of
chunk c-1 precisely one tick before chunk c needs it — no buffering), and
cuts the fill/drain bubble from v*(S-1) to 2*(S-1) chunk-ticks: the
interleaved win, with microbatch m's result ready at tick
S*((v-1) + (m//S)*v) + m%S + (S-1).

``remat=True`` wraps each block in jax.checkpoint (activation recompute in
the backward wave — the reference's recompute_interval inside pp stages).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim
    (shard dim 0 over the pp axis when placing)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def stack_block_params(per_block, num_stages, num_virtual=1):
    """[block0_tree, ... block{L-1}_tree] -> one tree with leading dims
    [S, k] (v==1) or [S, v, k] (v>1), where L = S*v*k and block
    j = (c*S + d)*k + i lands at [d, c, i] — i.e. device d's chunk c holds
    the k consecutive blocks of virtual stage c*S + d. Shard dim 0 over pp
    when placing."""
    L = len(per_block)
    S, v = int(num_stages), int(num_virtual)
    if L % (S * v) != 0:
        raise ValueError(
            f"{L} blocks cannot tile {S} stages x {v} virtual chunks"
        )
    k = L // (S * v)
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_block
    )
    if v == 1:
        return jax.tree_util.tree_map(
            lambda a: a.reshape((S, k) + a.shape[1:]), stacked
        )

    def _arrange(a):
        a = a.reshape((v, S, k) + a.shape[1:])  # axes (c, d, i, ...)
        return jnp.moveaxis(a, 1, 0)  # -> (d, c, i, ...)

    return jax.tree_util.tree_map(_arrange, stacked)


def microbatch_ready_ticks(num_microbatches, num_stages, num_virtual=1):
    """Tick at which microbatch m's final output appears on the last
    stage (see module docstring schedule)."""
    S, v = num_stages, num_virtual
    return [
        S * ((v - 1) + (m // S) * v) + m % S + (S - 1)
        for m in range(num_microbatches)
    ]


def pipeline_apply(block_fn, chunk_params, h_mb, axis_name="pp",
                   num_stages=None, num_virtual=1, remat=False):
    """Run the microbatch pipeline INSIDE a shard_map over ``axis_name``.

    block_fn(one_block_params, x) -> x
    chunk_params: local slice, leaves [1, k, ...] (v==1) or [1, v, k, ...]
        (the shard_map in_spec puts the stage dim first; squeezed here)
    h_mb: [M, ...microbatch...] activations entering stage 0 (replicated
        over the pp axis)
    Returns [M, ...] outputs of the LAST (virtual) stage, replicated
    over pp.
    """
    S = num_stages
    v = int(num_virtual)
    M = h_mb.shape[0]
    s_idx = jax.lax.axis_index(axis_name)
    chunk = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0), chunk_params)
    bf = jax.checkpoint(block_fn) if remat else block_fn

    def chunk_apply(blocks, x):
        def body(h, blk):
            return bf(blk, h), None

        h, _ = jax.lax.scan(body, x, blocks)
        return h

    if S <= 1:
        perm = None
    elif v > 1:
        # full ring: the wrap edge carries multi-pass activations
        perm = [(i, (i + 1) % S) for i in range(S)]
    else:
        # v==1: stage 0 always injects, so skip the dead wrap transfer
        perm = [(i, i + 1) for i in range(S - 1)]

    def tick(recv, t):
        r = jnp.maximum(t - s_idx, 0)  # local logical time
        sq = r // S
        c = sq % v
        m = (sq // v) * S + r % S
        x0 = h_mb[jnp.clip(m, 0, M - 1)]
        inject = jnp.logical_and(s_idx == 0, c == 0)
        x_in = jnp.where(inject, x0, recv)
        if v == 1:
            y = chunk_apply(chunk, x_in)
        else:
            blk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, c, 0, keepdims=False
                ),
                chunk,
            )
            y = chunk_apply(blk, x_in)
        send = jax.lax.ppermute(y, axis_name, perm) if perm else y
        return send, y

    touts = microbatch_ready_ticks(M, S, v)
    _, ys = jax.lax.scan(
        tick, jnp.zeros(h_mb.shape[1:], h_mb.dtype),
        jnp.arange(max(touts) + 1),
    )
    outs = jnp.take(ys, jnp.asarray(touts), axis=0)
    # only the last stage holds real outputs; raw psum replicates them.
    # NOTE: under unchecked shard_map, a replicated out_spec's transpose
    # hands each device ct/n — and psum's transpose (psum) sums those n
    # pieces back to the full ct, so the pair is exactly grad-correct.
    # (Do NOT swap in an identity-bwd allreduce here; that halves grads.)
    mask = (s_idx == S - 1).astype(outs.dtype)
    return jax.lax.psum(outs * mask, axis_name)


def make_pipeline_fn(block_fn, num_stages, mesh, axis_name="pp",
                     extra_in_specs=None, num_virtual=1, remat=False,
                     manual_axes=None):
    """Build a jittable fn(stacked_params, h_mb) -> outs where
    stacked_params leaves are [num_stages, (v,) blocks, ...] sharded over
    ``axis_name`` on dim 0, h_mb is [M, ...] (replicated over pp; may
    carry other-axis shardings via ``extra_in_specs``).

    manual_axes: axes the shard_map body is manual over (default: all mesh
    axes). Pass {axis_name} to leave the other axes (dp/mp/...) in GSPMD
    auto mode so sharding constraints inside block_fn keep working — the
    TP-inside-PP composition path.
    """
    from jax.sharding import PartitionSpec as P

    h_spec = extra_in_specs if extra_in_specs is not None else P()

    def fn(stacked_params, h_mb):
        body = lambda cp, h: pipeline_apply(
            block_fn, cp, h, axis_name=axis_name, num_stages=num_stages,
            num_virtual=num_virtual, remat=remat,
        )
        spec_params = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params
        )
        kwargs = {}
        if manual_axes is not None:
            kwargs["axis_names"] = set(manual_axes)
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(spec_params, h_spec),
            out_specs=h_spec,
            check_vma=False,
            **kwargs,
        )(stacked_params, h_mb)

    return fn
