"""Context/segment parallelism (sep axis): ring attention + Ulysses.

Reference parity: the `sep` axis of HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py) plus the
ring-flash-attention / all-to-all (Ulysses) attention implementations the
PaddleNLP layer builds on those hooks (SURVEY.md §5 long-context; both are
in-scope per §7 stage 9). Unverified paths — reference mount empty.

TPU-first design: the sequence dim of q/k/v ([B, S, H, D], paddle flash
layout) is sharded over the ``sep`` mesh axis. Two exchange strategies:

- **Ring attention** (`ring_flash_attention`): K/V blocks rotate around the
  sep ring via `ppermute` while each device's Q stays resident; partial
  attention is merged with the numerically-stable online-softmax
  accumulation (running max / normalizer), so the result is EXACTLY full
  attention — memory per device stays O(S/sep · S/sep) per step and the
  KV transfer rides the ICI ring one hop at a time.
- **Ulysses** (`ulysses_attention`): two `all_to_all`s re-partition
  [B, S/sep, H, D] -> [B, S, H/sep, D], attend over the full sequence with
  a head subset, and swap back. Cheaper at moderate S (2 collectives vs
  sep-1 permutes) but requires num_heads % sep == 0.

Both are differentiable end-to-end (ppermute/all_to_all have transpose
rules; jax.vjp of the shard_map body gives the reverse ring), composable
with the dp/mp axes, and run inside compiled steps.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core import dispatch
from . import mesh as mesh_mod

_NEG = -1e30


def _ring_attention_local(q, k, v, idx, *, axis, seg, causal, scale):
    """Local shard_map body. q/k/v: local [B, Sl, H, D] blocks; ``idx`` is
    this shard's position on the sep ring, delivered as a sep-sharded
    iota operand ([1] locally) instead of ``lax.axis_index`` — whose
    lowering binds every other mesh axis manually and therefore cannot
    nest inside the compiled pipeline's pp-manual shard_map."""
    p = idx[0]
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    b, h, sl, d = qt.shape
    m = jnp.full((b, h, sl), _NEG, jnp.float32)  # running row max
    l = jnp.zeros((b, h, sl), jnp.float32)  # running normalizer
    acc = jnp.zeros((b, h, sl, d), jnp.float32)
    qpos = p * sl + jnp.arange(sl)
    kk, vv = kt, vt
    perm = [(r, (r + 1) % seg) for r in range(seg)]
    for i in range(seg):
        j = (p - i) % seg  # which global KV block this device holds now
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kk) * scale
        if causal:
            kpos = j * sl + jnp.arange(sl)
            s = jnp.where(
                (kpos[None, :] <= qpos[:, None])[None, None], s, _NEG
            )
        m_new = jnp.maximum(m, s.max(-1))
        corr = jnp.exp(m - m_new)
        # fully-masked rows: s == m_new == _NEG would give exp(0)=1; zero them
        pexp = jnp.where(s <= _NEG / 2, 0.0, jnp.exp(s - m_new[..., None]))
        l = l * corr + pexp.sum(-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pexp, vv
        )
        m = m_new
        if i < seg - 1:
            kk = jax.lax.ppermute(kk, axis, perm)
            vv = jax.lax.ppermute(vv, axis, perm)
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _ulysses_local(q, k, v, idx, *, axis, causal, scale):
    """Local shard_map body. q/k/v: local [B, Sl, H, D] blocks. ``idx``
    (ring position, unused here) keeps the shard_map signature uniform."""
    del idx

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(
            x, axis, split_axis=split_axis, concat_axis=concat_axis,
            tiled=True,
        )

    qg = a2a(q, 2, 1)  # [B, S, H/sep, D]
    kg = a2a(k, 2, 1)
    vg = a2a(v, 2, 1)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", qg.astype(jnp.float32), kg.astype(jnp.float32)
    ) * scale
    if causal:
        sq = s.shape[-1]
        s = jnp.where(
            (jnp.arange(sq)[None, :] <= jnp.arange(sq)[:, None])[None, None],
            s, _NEG,
        )
    pr = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", pr, vg.astype(jnp.float32))
    return a2a(out.astype(q.dtype), 1, 2)


def _sep_spec(axis):
    return P(None, axis, None, None)


def _sharded(kind, body, q, k, v, axis):
    mesh = mesh_mod.get_mesh()
    # nested-shard_map composition (sep attention INSIDE the compiled pp
    # ring): when the trace already sits inside a shard_map whose mesh has
    # Manual axes (the pipeline is manual over pp only), the inner
    # shard_map must be built on the context's abstract mesh
    try:
        ctx = jax.sharding.get_abstract_mesh()
        if getattr(ctx, "axis_names", ()) and any(
            t == jax.sharding.AxisType.Manual
            for t in getattr(ctx, "axis_types", ())
        ):
            mesh = ctx
    except Exception:
        pass
    spec = _sep_spec(axis)
    seg = mesh_mod.axis_size(axis)
    # manual over sep ONLY: dp/mp stay in GSPMD auto mode so batch/head
    # shardings compose (and pp, when present, stays the outer ring's)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, P(axis)),
        out_specs=spec, check_vma=True, axis_names={axis},
    )
    idx = jnp.arange(seg, dtype=jnp.int32)
    return dispatch.apply(
        kind, lambda qv, kv, vv: fn(qv, kv, vv, idx), (q, k, v),
        cache=False,
    )


def ring_flash_attention(q, k, v, causal=True, axis=None):
    """Exact full attention over a sep-sharded sequence via KV rotation.

    q/k/v: [B, S, H, D] Tensors with S sharded over the ``sep`` mesh axis
    (replicated inputs work too — the shard_map re-partitions them).
    Falls back to plain attention when the sep degree is 1.
    """
    axis = axis or "sep"
    seg = mesh_mod.axis_size(axis)
    scale = 1.0 / math.sqrt(q.shape[-1])
    if seg <= 1:
        from ..nn.functional.attention import scaled_dot_product_attention

        return scaled_dot_product_attention(q, k, v, is_causal=causal)
    body = functools.partial(
        _ring_attention_local, axis=axis, seg=seg, causal=causal,
        scale=scale,
    )
    return _sharded("ring_flash_attention", body, q, k, v, axis)


def ulysses_attention(q, k, v, causal=True, axis=None):
    """Full attention over a sep-sharded sequence via head<->seq all-to-all
    (DeepSpeed-Ulysses). Requires num_heads % sep_degree == 0."""
    axis = axis or "sep"
    seg = mesh_mod.axis_size(axis)
    scale = 1.0 / math.sqrt(q.shape[-1])
    if seg <= 1:
        from ..nn.functional.attention import scaled_dot_product_attention

        return scaled_dot_product_attention(q, k, v, is_causal=causal)
    if q.shape[2] % seg != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[2]}) divisible "
            f"by the sep degree ({seg}); use ring_flash_attention instead"
        )
    body = functools.partial(
        _ulysses_local, axis=axis, causal=causal, scale=scale
    )
    return _sharded("ulysses_attention", body, q, k, v, axis)
