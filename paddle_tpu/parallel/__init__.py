"""paddle_tpu.parallel — the TPU-native parallelism substrate.

This is the layer the reference does NOT have: where Paddle hand-schedules
NCCL (SURVEY.md §2.2), paddle_tpu expresses every parallelism axis as a
jax.sharding.Mesh dimension and lets XLA/SPMD insert collectives over
ICI/DCN. Everything in paddle_tpu.distributed (the paddle-parity API) is a
veneer over this module.
"""
from .mesh import (  # noqa: F401
    axis_index,
    axis_size,
    get_mesh,
    global_mesh_shape,
    init_mesh,
    mesh_defined,
    set_mesh,
)
from . import collectives  # noqa: F401
from . import layout  # noqa: F401
from .layout import (  # noqa: F401
    LayoutPolicy,
    get_policy,
    register_policy,
    set_policy,
    use_policy,
)
from .sep_ops import ring_flash_attention, ulysses_attention  # noqa: F401
