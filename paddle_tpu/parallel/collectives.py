"""Collective primitives over mesh axes.

Reference parity: the C++ ProcessGroup collective set (SURVEY.md §2.2) —
but expressed the TPU way: these are *traceable* functions used inside
shard_map'd / jitted parallel programs, compiled by XLA into ICI
collectives. The eager ProcessGroupICI (distributed/process_group.py) calls
the same primitives through cached jitted executables.

Two families:
- in-trace (lax.*) wrappers: psum/pmean/all_gather/reduce_scatter/
  all_to_all/ppermute/broadcast_in_axis — usable inside shard_map bodies.
- host-level helpers building jitted shard_map executables for one-shot
  eager collectives on sharded global arrays.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import get_mesh, mesh_epoch

# ------------------------------------------------------------ in-trace ops


def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return jax.lax.pmin(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_axis=0):
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_axis, tiled=True
    )


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled,
    )


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def broadcast_from(x, axis_name, src=0):
    """Everyone gets rank-src's value (inside shard_map)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


# ---------------------------------------------- eager executables (cached)


@functools.lru_cache(maxsize=256)
def _allreduce_exec(mesh_epoch_key, axis, op, shape, dtype):
    mesh = get_mesh()
    reducer = {"sum": psum, "mean": pmean, "max": pmax, "min": pmin}[op]

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
    )
    def f(x):
        return reducer(x, axis)

    return f


def eager_all_reduce(global_array, axis, op="sum"):
    """All-reduce a global array whose leading dim is sharded over ``axis``.

    Each "rank" (mesh coordinate on axis) owns one slice along dim 0;
    afterwards every slice holds the reduction — eager ProcessGroup
    semantics expressed on a sharded array.
    """
    mesh = get_mesh()
    f = _allreduce_exec(
        mesh_epoch(), axis, op,
        tuple(global_array.shape), str(global_array.dtype),
    )
    return f(global_array)


def shard_batch(arr, axis="dp", mesh=None):
    """Place a host batch onto the mesh sharded along dim 0 (input path)."""
    mesh = mesh or get_mesh()
    spec = [None] * arr.ndim
    spec[0] = axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def replicate(arr, mesh=None):
    mesh = mesh or get_mesh()
    return jax.device_put(arr, NamedSharding(mesh, P()))
