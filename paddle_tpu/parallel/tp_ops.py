"""Explicit Megatron-style TP primitives for shard_map bodies.

Reference parity: the collective algebra inside
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py
(unverified, mount empty): identity-forward/allreduce-backward wrappers,
partial-sum row matmuls, masked vocab-parallel embedding lookup and the
Megatron vocab-parallel cross entropy.

Two TP styles exist in this framework (tested against each other and a
single-device gold run):
1. GSPMD sharding-constraint layers (mp_layers.py) — the default: weights
   carry NamedShardings, XLA's partitioner inserts the collectives.
2. These functions — the explicit form, used inside jax.shard_map when a
   schedule needs manual control over where each collective happens.

All functions here take *local shards* and a mesh axis name, and are valid
only inside shard_map/pmap-style named-axis contexts.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod


def identity_fwd_allreduce_bwd(x, axis_name):
    """Megatron f: forward identity, backward all-reduce (enter a column-
    parallel region with replicated input)."""

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, ct):
        return (jax.lax.psum(ct, axis_name),)

    f.defvjp(fwd, bwd)
    return f(x)


def allreduce_fwd_identity_bwd(x, axis_name):
    """Megatron g: forward all-reduce, backward identity (leave a row-
    parallel region)."""

    @jax.custom_vjp
    def f(v):
        return jax.lax.psum(v, axis_name)

    def fwd(v):
        return jax.lax.psum(v, axis_name), None

    def bwd(_, ct):
        return (ct,)

    f.defvjp(fwd, bwd)
    return f(x)


def column_parallel_linear(x, w_shard, b_shard=None, axis_name="mp",
                           gather_output=False):
    """x replicated, w [in, out/mp] local shard -> local [.., out/mp] (or
    gathered [.., out] when gather_output)."""
    x = identity_fwd_allreduce_bwd(x, axis_name)
    y = jnp.matmul(x, w_shard)
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        y = jax.lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, w_shard, bias=None, axis_name="mp"):
    """x [.., in/mp] local, w [in/mp, out] local -> replicated [.., out]
    (partial products all-reduced; bias added once, after the reduce)."""
    y = allreduce_fwd_identity_bwd(jnp.matmul(x_shard, w_shard), axis_name)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embedding(ids, table_shard, axis_name="mp"):
    """ids replicated ints, table [vocab/mp, H] local shard -> replicated
    [.., H]: masked local lookup + all-reduce."""
    n_local = table_shard.shape[0]
    start = jax.lax.axis_index(axis_name) * n_local
    local = ids - start
    ok = (local >= 0) & (local < n_local)
    rows = jnp.take(table_shard, jnp.clip(local, 0, n_local - 1), axis=0)
    rows = jnp.where(ok[..., None], rows, jnp.zeros_like(rows))
    return allreduce_fwd_identity_bwd(rows, axis_name)


def vocab_parallel_cross_entropy(logits_shard, labels, axis_name="mp"):
    """Megatron parallel softmax CE: logits [.., V/mp] local shards,
    labels replicated ints -> per-example loss, replicated.

    Never materializes the full-vocab logits: max and sum-exp ride
    psum/pmax over the axis, the label logit is picked from whichever
    shard owns it.
    """
    n_local = logits_shard.shape[-1]
    m = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(logits_shard, axis=-1)), axis_name
    )
    shifted = logits_shard - m[..., None]
    # allreduce_fwd_identity_bwd pins the psum transpose to identity (each
    # rank's local term receives the replicated cotangent once); a raw
    # lax.psum would re-sum the replicated cotangent across ranks
    sumexp = allreduce_fwd_identity_bwd(
        jnp.sum(jnp.exp(shifted), axis=-1), axis_name
    )

    start = jax.lax.axis_index(axis_name) * n_local
    local = labels - start
    ok = (local >= 0) & (local < n_local)
    picked = jnp.take_along_axis(
        shifted, jnp.clip(local, 0, n_local - 1)[..., None], axis=-1
    )[..., 0]
    label_logit = allreduce_fwd_identity_bwd(
        jnp.where(ok, picked, jnp.zeros_like(picked)), axis_name
    )
    return jnp.log(sumexp) - label_logit


def vocab_parallel_cross_entropy_grad(logits_shard, labels, ct,
                                      axis_name="mp", ignore_index=None):
    """Analytic local-shard gradient of the Megatron parallel CE:
    (softmax_local - onehot_local) * ct, zero on ignored rows. Used as
    the hand-written backward of the SPMD wrapper below — recomputing
    softmax per shard keeps the residuals at just (logits, labels) and
    sidesteps shard_map's replicated-cotangent transpose entirely."""
    lg = logits_shard.astype(jnp.float32)
    n_local = lg.shape[-1]
    m = jax.lax.pmax(jnp.max(lg, axis=-1), axis_name)
    e = jnp.exp(lg - m[..., None])
    sumexp = jax.lax.psum(jnp.sum(e, axis=-1), axis_name)
    soft = e / sumexp[..., None]
    start = jax.lax.axis_index(axis_name) * n_local
    local = labels - start
    onehot = (
        local[..., None] == jnp.arange(n_local)[None, :]
    ).astype(jnp.float32)
    ct = ct.astype(jnp.float32)
    if ignore_index is not None:
        ct = jnp.where(labels != ignore_index, ct, 0.0)
    return ((soft - onehot) * ct[..., None]).astype(logits_shard.dtype)


def _loss_lead_spec(n_rows, lead_axes):
    """The flattened-token dim's spec entry: shard over every lead axis
    (dp, then sep) whose degree divides evenly; replicate otherwise."""
    sizes = mesh_mod.global_mesh_shape()
    lead, prod = [], 1
    for a in lead_axes:
        d = sizes.get(a, 1)
        if d > 1 and n_rows % (prod * d) == 0:
            lead.append(a)
            prod *= d
    return tuple(lead) if lead else None


def vocab_parallel_cross_entropy_spmd(logits, labels, *, axis_name="mp",
                                      lead_axes=("dp", "sep"),
                                      ignore_index=-100):
    """Global-array form of the Megatron parallel CE for GSPMD programs.

    logits: [..., V] with V sharded over ``axis_name`` on the installed
    mesh (the gather_output=False column head's layout); labels:
    replicated-or-batch-sharded ints. Returns per-token loss (zero at
    ``ignore_index`` rows — F.cross_entropy reduction='none' parity),
    fp32, with the SAME leading shape.

    The body runs in a shard_map manual over ALL mesh axes (works on
    every jax line this repo supports; partial-manual is not required
    because the loss sits outside the pipeline ring), so per chip only
    the LOCAL [rows, V/mp] fp32 block ever exists — the full-vocab fp32
    logits array is never materialized, which is the 7B memory lever
    (lower_7b pins this on the lowered module's avals). The backward is
    a second fully-sharded shard_map over the analytic gradient — a
    replicated-output cotangent never meets shard_map's transpose."""
    mesh = mesh_mod.get_mesh()
    lead_shape = tuple(logits.shape[:-1])
    V = int(logits.shape[-1])
    n_rows = int(np.prod(lead_shape, dtype=np.int64)) if lead_shape else 1
    lead = _loss_lead_spec(n_rows, lead_axes)
    spec_l = P(lead, axis_name)
    spec_y = P(lead)

    def fwd_body(lg, lb):
        ce = vocab_parallel_cross_entropy(
            lg.astype(jnp.float32), lb, axis_name=axis_name
        )
        return jnp.where(lb != ignore_index, ce, 0.0)

    def bwd_body(lg, lb, ct):
        return vocab_parallel_cross_entropy_grad(
            lg, lb, ct, axis_name=axis_name, ignore_index=ignore_index
        )

    fwd_sm = jax.shard_map(
        fwd_body, mesh=mesh, in_specs=(spec_l, spec_y),
        out_specs=spec_y, check_vma=False,
    )
    bwd_sm = jax.shard_map(
        bwd_body, mesh=mesh, in_specs=(spec_l, spec_y, spec_y),
        out_specs=spec_l, check_vma=False,
    )

    @jax.custom_vjp
    def ce(lg, lb):
        return fwd_sm(lg, lb)

    def ce_fwd(lg, lb):
        return fwd_sm(lg, lb), (lg, lb)

    def ce_bwd(res, ct):
        lg, lb = res
        return (
            bwd_sm(lg, lb, ct),
            np.zeros(lb.shape, jax.dtypes.float0),  # int labels: no grad
        )

    ce.defvjp(ce_fwd, ce_bwd)
    flat = ce(
        logits.reshape((n_rows, V)), labels.reshape((n_rows,))
    )
    return flat.reshape(lead_shape)
