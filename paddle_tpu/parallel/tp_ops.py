"""Explicit Megatron-style TP primitives for shard_map bodies.

Reference parity: the collective algebra inside
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py
(unverified, mount empty): identity-forward/allreduce-backward wrappers,
partial-sum row matmuls, masked vocab-parallel embedding lookup and the
Megatron vocab-parallel cross entropy.

Two TP styles exist in this framework (tested against each other and a
single-device gold run):
1. GSPMD sharding-constraint layers (mp_layers.py) — the default: weights
   carry NamedShardings, XLA's partitioner inserts the collectives.
2. These functions — the explicit form, used inside jax.shard_map when a
   schedule needs manual control over where each collective happens.

All functions here take *local shards* and a mesh axis name, and are valid
only inside shard_map/pmap-style named-axis contexts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def identity_fwd_allreduce_bwd(x, axis_name):
    """Megatron f: forward identity, backward all-reduce (enter a column-
    parallel region with replicated input)."""

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, ct):
        return (jax.lax.psum(ct, axis_name),)

    f.defvjp(fwd, bwd)
    return f(x)


def allreduce_fwd_identity_bwd(x, axis_name):
    """Megatron g: forward all-reduce, backward identity (leave a row-
    parallel region)."""

    @jax.custom_vjp
    def f(v):
        return jax.lax.psum(v, axis_name)

    def fwd(v):
        return jax.lax.psum(v, axis_name), None

    def bwd(_, ct):
        return (ct,)

    f.defvjp(fwd, bwd)
    return f(x)


def column_parallel_linear(x, w_shard, b_shard=None, axis_name="mp",
                           gather_output=False):
    """x replicated, w [in, out/mp] local shard -> local [.., out/mp] (or
    gathered [.., out] when gather_output)."""
    x = identity_fwd_allreduce_bwd(x, axis_name)
    y = jnp.matmul(x, w_shard)
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        y = jax.lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, w_shard, bias=None, axis_name="mp"):
    """x [.., in/mp] local, w [in/mp, out] local -> replicated [.., out]
    (partial products all-reduced; bias added once, after the reduce)."""
    y = allreduce_fwd_identity_bwd(jnp.matmul(x_shard, w_shard), axis_name)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embedding(ids, table_shard, axis_name="mp"):
    """ids replicated ints, table [vocab/mp, H] local shard -> replicated
    [.., H]: masked local lookup + all-reduce."""
    n_local = table_shard.shape[0]
    start = jax.lax.axis_index(axis_name) * n_local
    local = ids - start
    ok = (local >= 0) & (local < n_local)
    rows = jnp.take(table_shard, jnp.clip(local, 0, n_local - 1), axis=0)
    rows = jnp.where(ok[..., None], rows, jnp.zeros_like(rows))
    return allreduce_fwd_identity_bwd(rows, axis_name)


def vocab_parallel_cross_entropy(logits_shard, labels, axis_name="mp"):
    """Megatron parallel softmax CE: logits [.., V/mp] local shards,
    labels replicated ints -> per-example loss, replicated.

    Never materializes the full-vocab logits: max and sum-exp ride
    psum/pmax over the axis, the label logit is picked from whichever
    shard owns it.
    """
    n_local = logits_shard.shape[-1]
    m = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(logits_shard, axis=-1)), axis_name
    )
    shifted = logits_shard - m[..., None]
    # allreduce_fwd_identity_bwd pins the psum transpose to identity (each
    # rank's local term receives the replicated cotangent once); a raw
    # lax.psum would re-sum the replicated cotangent across ranks
    sumexp = allreduce_fwd_identity_bwd(
        jnp.sum(jnp.exp(shifted), axis=-1), axis_name
    )

    start = jax.lax.axis_index(axis_name) * n_local
    local = labels - start
    ok = (local >= 0) & (local < n_local)
    picked = jnp.take_along_axis(
        shifted, jnp.clip(local, 0, n_local - 1)[..., None], axis=-1
    )[..., 0]
    label_logit = allreduce_fwd_identity_bwd(
        jnp.where(ok, picked, jnp.zeros_like(picked)), axis_name
    )
    return jnp.log(sumexp) - label_logit
