"""First-class, swappable sharding layout policy (the SpecLayout idea).

Before this module the hybrid tp x pp x dp layout lived as per-model
annotations: every TP layer hard-coded its PartitionSpec, the optimizer
state implicitly mirrored the parameter placement, and changing any of
it meant editing model code. A :class:`LayoutPolicy` promotes the layout
to ONE named object — a set of rules per parameter family (embedding /
column weight / row weight / norm / head / optimizer state), resolved
against the live ``parallel.mesh`` — so the whole-cluster layout is a
swappable value, not a property scattered through the model zoo.

The default policy (``tp-pp-dp``) reproduces the pre-policy annotations
byte-for-byte. Two more ship with the framework:

- ``pp-sharded-state``: optimizer moments AND fp32 master params shard
  over the pp axis too (they are pp-replicated in the default layout —
  each pp rank stores every block's state but only steps its own
  blocks), and the causal-LM loss runs the vocab-parallel cross entropy
  so the fp32 logits block stays vocab-sharded end to end. At the
  v5p-64 7B geometry this drops the analytic per-chip budget from
  29.4 to 18.4 GiB (see tools/lower_7b.py).
- ``long-context``: everything above plus sequence/context parallelism —
  decoder attention routes through the sep-axis ring
  (parallel.sep_ops.ring_flash_attention), funding S=8192 contexts from
  the freed state headroom.

Swap layouts without touching model code::

    from paddle_tpu.parallel import layout
    with layout.use_policy("pp-sharded-state"):
        trainer = CompiledPipelineTrainStep(net, loss, opt, ...)

Policies are immutable; derive variants with :func:`dataclasses.replace`
and :func:`register_policy` them under a new name.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
from dataclasses import dataclass

from jax.sharding import NamedSharding, PartitionSpec as P

from . import mesh as mesh_mod

# parameter families the rules cover. "column"/"row" follow the Megatron
# naming: a column-parallel weight [in, out] shards its OUTPUT features,
# a row-parallel weight [in, out] shards its INPUT features.
FAMILIES = (
    "embedding",       # [vocab, hidden] — vocab rows over mp
    "column_weight",   # [in, out] — out over mp
    "column_bias",     # [out] — over mp
    "row_weight",      # [in, out] — in over mp
    "replicated",      # norms, row biases, scalars
    "lm_head",         # [hidden, vocab] — vocab cols over mp
)


@dataclass(frozen=True)
class LayoutPolicy:
    """Named rules mapping parameter families to PartitionSpecs plus the
    memory levers that ride on the seam. Frozen: a policy is a value."""

    name: str
    dp_axis: str = "dp"
    pp_axis: str = "pp"
    mp_axis: str = "mp"
    sep_axis: str = "sep"
    # --- levers -------------------------------------------------------
    #: causal-LM loss runs tp_ops vocab-parallel CE over mp-sharded
    #: logits (the full-vocab fp32 block never exists per chip)
    vocab_parallel_loss: bool = False
    #: optimizer moments shard over pp (ZeRO-1 along the pipeline axis)
    pp_shard_optimizer_state: bool = False
    #: fp32 master params shard over pp at rest (re-gathered in-trace by
    #: the pipeline's stacked P('pp') constraint for compute)
    pp_shard_master_params: bool = False
    #: decoder attention routes through the sep-axis ring when the mesh
    #: carries a sep degree > 1 (long-context regime)
    use_sep_attention: bool = False

    # ------------------------------------------------- family rules
    def spec(self, family: str) -> P:
        """The PartitionSpec for a parameter family."""
        mp = self.mp_axis
        table = {
            "embedding": P(mp, None),
            "column_weight": P(None, mp),
            "column_bias": P(mp),
            "row_weight": P(mp, None),
            "replicated": P(),
            "lm_head": P(None, mp),
        }
        if family not in table:
            raise KeyError(
                f"unknown parameter family {family!r}; families: "
                f"{FAMILIES}"
            )
        return table[family]

    def batch_spec(self, ndim: int = 2) -> P:
        """Input batches ([B, S, ...]): batch dim over dp; the sequence
        dim shards over sep as well when this policy routes attention
        through the sep ring AND the live mesh carries sep degree > 1
        (a degree-1 sep entry is a no-op but kept out for clarity)."""
        rest = [None] * (ndim - 1)
        if (
            self.use_sep_attention
            and ndim >= 2
            and mesh_mod.mesh_defined()
            and mesh_mod.axis_size(self.sep_axis) > 1
        ):
            rest[0] = self.sep_axis
        return P(self.dp_axis, *rest)

    def loss_lead_axes(self) -> tuple:
        """Mesh axes the flattened [B*S] loss dim may shard over (the
        vocab-parallel CE shard_map's lead spec), outermost first."""
        return (self.dp_axis, self.sep_axis)

    def axis_names(self) -> tuple:
        """Every mesh axis this policy can name in specs/collectives
        (consumed by the jaxpr linter's collective-mesh-mismatch rule)."""
        return (self.dp_axis, self.pp_axis, self.mp_axis, self.sep_axis)

    # ------------------------------------------- optimizer-state rules
    def pp_extend_spec(self, base_spec, shape):
        """``base_spec`` with the pp axis added on the first unsharded,
        pp-divisible dim — the generic state-sharding rule. Returns None
        when no dim is eligible (the leaf stays on its base layout)."""
        if not mesh_mod.mesh_defined():
            return None
        pp = mesh_mod.axis_size(self.pp_axis)
        if pp <= 1:
            return None
        entries = list(base_spec) if base_spec is not None else []
        entries += [None] * (len(shape) - len(entries))
        for e in entries:  # already pp-sharded (steady-state layout)
            if e == self.pp_axis or (
                isinstance(e, tuple) and self.pp_axis in e
            ):
                return None
        for i, d in enumerate(shape):
            if entries[i] is None and d % pp == 0 and d >= pp:
                entries[i] = self.pp_axis
                return P(*entries)
        return None

    def _pp_extended_sharding(self, param_value):
        """``param_value``'s own layout extended over pp, as a
        NamedSharding on the live mesh (None when no dim is eligible —
        the leaf mirrors the param placement)."""
        base = getattr(param_value, "sharding", None)
        base_spec = getattr(base, "spec", None) if isinstance(
            base, NamedSharding
        ) else None
        shape = tuple(getattr(param_value, "shape", ()) or ())
        ext = self.pp_extend_spec(base_spec, shape)
        if ext is None:
            return None
        return NamedSharding(mesh_mod.get_mesh(), ext)

    def optimizer_state_sharding(self, param_value):
        """NamedSharding for an optimizer accumulator of ``param_value``
        under this policy, or None to mirror the param placement (the
        default layout). The rule: moments live wherever the param
        lives, plus the pp axis when the lever is on."""
        if not self.pp_shard_optimizer_state:
            return None
        return self._pp_extended_sharding(param_value)

    def master_param_sharding(self, param_value):
        """Like :meth:`optimizer_state_sharding` but for the fp32 master
        params themselves (the ``pp_shard_master_params`` lever)."""
        if not self.pp_shard_master_params:
            return None
        return self._pp_extended_sharding(param_value)

    def describe(self) -> dict:
        """Self-describing record for bench/lower JSON outputs."""
        return {
            "name": self.name,
            "axes": {"dp": self.dp_axis, "pp": self.pp_axis,
                     "mp": self.mp_axis, "sep": self.sep_axis},
            "vocab_parallel_loss": self.vocab_parallel_loss,
            "pp_shard_optimizer_state": self.pp_shard_optimizer_state,
            "pp_shard_master_params": self.pp_shard_master_params,
            "use_sep_attention": self.use_sep_attention,
        }


# --------------------------------------------------------------- registry
_LOCK = threading.Lock()
_POLICIES: dict = {}
# the ACTIVE slot is THREAD-LOCAL: every CompiledTrainStep step wraps
# itself in use_policy(<captured policy>), so a process-global slot
# would let concurrent trainers (or a serving thread next to a train
# loop) clobber each other's layout mid-trace and leak the last
# restore. Per-thread state keeps each trainer's swap isolated; the
# registry itself stays process-global.
_ACTIVE = threading.local()


def _active_policy():
    return getattr(_ACTIVE, "policy", None)

#: the pre-policy layout, byte-identical to the historical per-model
#: annotations (mp_layers hard-coded specs, pp-replicated state)
DEFAULT_POLICY = LayoutPolicy(name="tp-pp-dp")

PP_SHARDED_STATE = LayoutPolicy(
    name="pp-sharded-state",
    vocab_parallel_loss=True,
    pp_shard_optimizer_state=True,
    pp_shard_master_params=True,
)

LONG_CONTEXT = LayoutPolicy(
    name="long-context",
    vocab_parallel_loss=True,
    pp_shard_optimizer_state=True,
    pp_shard_master_params=True,
    use_sep_attention=True,
)


def register_policy(policy: LayoutPolicy):
    """Add (or replace) a policy in the registry under ``policy.name``."""
    if not isinstance(policy, LayoutPolicy):
        raise TypeError(f"expected a LayoutPolicy, got {type(policy)}")
    with _LOCK:
        _POLICIES[policy.name] = policy
    return policy


for _p in (DEFAULT_POLICY, PP_SHARDED_STATE, LONG_CONTEXT):
    register_policy(_p)


def list_policies() -> tuple:
    return tuple(sorted(_POLICIES))


def resolve(name_or_policy) -> LayoutPolicy:
    """A LayoutPolicy from a registry name or a policy instance."""
    if isinstance(name_or_policy, LayoutPolicy):
        return name_or_policy
    try:
        return _POLICIES[name_or_policy]
    except KeyError:
        raise KeyError(
            f"unknown layout policy {name_or_policy!r}; registered: "
            f"{list_policies()}"
        ) from None


def get_policy() -> LayoutPolicy:
    """This thread's active policy (the default tp-pp-dp layout until
    swapped)."""
    return _active_policy() or DEFAULT_POLICY


def policy_installed() -> bool:
    """True when a policy was EXPLICITLY installed on this thread
    (set_policy / use_policy) rather than the implicit default —
    consumers that relax checks for policy-declared axes (the jaxpr
    linter) key on this so the default state keeps full strictness."""
    return _active_policy() is not None


def set_policy(name_or_policy):
    """Install a policy for THIS thread (None = back to the implicit
    default). Returns the RAW previous slot — None when no policy was
    installed — so `prev = set_policy(p) ... set_policy(prev)` restores
    the implicit-default state exactly instead of promoting it to an
    explicitly installed default (which would flip
    :func:`policy_installed` and relax the jaxpr linter for the rest of
    the thread)."""
    prev = _active_policy()
    _ACTIVE.policy = (
        resolve(name_or_policy) if name_or_policy is not None else None
    )
    return prev


@contextlib.contextmanager
def use_policy(name_or_policy):
    """Scoped policy swap (always restores the previous layout)."""
    prev = set_policy(name_or_policy)
    try:
        yield get_policy()
    finally:
        set_policy(prev)


def derive(base, name, **overrides) -> LayoutPolicy:
    """Register a variant of ``base`` with fields replaced (the policy
    objects are frozen — deriving is how custom layouts are made)."""
    pol = dataclasses.replace(resolve(base), name=name, **overrides)
    return register_policy(pol)


def accumulator_sharding(param_value):
    """Placement for a fresh optimizer accumulator of ``param_value``
    under the ACTIVE policy (None = mirror the param; consumed by
    Optimizer._acc so eager state is born sharded, not resharded on the
    first compiled step). Every legitimate no-op path returns None from
    the policy itself — a raise here is a real bug and must surface,
    not silently degrade 7B state to full-size-per-chip placement."""
    return get_policy().optimizer_state_sharding(param_value)
