"""Global device mesh management.

The hybrid topology (dp/sharding/pp/sep/mp/ep — SURVEY.md §2.3) is ONE
jax.sharding.Mesh whose axis order follows the reference's
CommunicateTopology convention: outermost-first [dp, pp, sharding, sep, mp]
(+ ep folded over dp×sharding for MoE). Mesh construction is DCN-aware:
when multiple slices/processes exist, the outermost axis maps across hosts
(DCN) and inner axes stay on ICI — jax's device order already enumerates
ICI-adjacent devices contiguously, so splitting outer-first achieves this.
"""
from __future__ import annotations

import threading

import numpy as np

import jax
from jax.sharding import Mesh

_LOCK = threading.Lock()
# epoch increments on every set_mesh so executable caches keyed on it can
# never alias a recycled id() of a GC'd mesh
_STATE = {"mesh": None, "epoch": 0}

# canonical axis order, outermost first — MUST match the order fleet's
# CommunicateTopology builds (reference: python/paddle/distributed/fleet/
# base/topology.py — unverified)
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")


def init_mesh(axes=None, devices=None):
    """Create + install the global mesh.

    axes: dict axis_name -> degree (product must equal device count; a
    single -1 degree is inferred). Default: {'dp': n_devices}.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if axes is None:
        axes = {"dp": n}
    names, degrees = [], []
    for k, v in axes.items():
        names.append(k)
        degrees.append(int(v))
    if -1 in degrees:
        known = int(np.prod([d for d in degrees if d != -1]))
        degrees[degrees.index(-1)] = n // known
    total = int(np.prod(degrees))
    if total != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, degrees))} need {total} devices, "
            f"have {n}"
        )
    arr = np.array(devs).reshape(degrees)
    mesh = Mesh(arr, axis_names=tuple(names))
    set_mesh(mesh)
    return mesh


def set_mesh(mesh):
    with _LOCK:
        _STATE["mesh"] = mesh
        _STATE["epoch"] += 1


def mesh_epoch() -> int:
    """Stable identity for executable caches (bumped by every set_mesh)."""
    return _STATE["epoch"]


def get_mesh() -> Mesh:
    m = _STATE["mesh"]
    if m is None:
        m = init_mesh()
    return m


def mesh_defined() -> bool:
    return _STATE["mesh"] is not None


def global_mesh_shape() -> dict:
    m = get_mesh()
    return dict(zip(m.axis_names, m.devices.shape))


def axis_size(name: str) -> int:
    return global_mesh_shape().get(name, 1)


def axis_index(name: str):
    """Inside shard_map: this device's coordinate along axis ``name``."""
    return jax.lax.axis_index(name)
