"""Fused TPU kernels (Pallas) behind the reference's fused-op API names.

Reference parity: paddle/phi/kernels/fusion/gpu/* + flash_attn third-party
lib (unverified, mount empty). Each module provides a Pallas TPU kernel and
a composed-jnp fallback (CPU/CI); call sites pick automatically.

Selection is measurement-driven: ``autotune`` holds the block-size
autotuner (measured search + persistent per-device result cache, see
``tools/kernel_tune.py``); flash attention and the fusion kernels
(``fused_rope_attention``, ``fused_norm_matmul``) resolve their block
configs through it, and publish selection/fallback decisions as
``paddle_kernels_*`` registry metrics.
"""
from . import autotune  # noqa: F401
from . import flash_attention  # noqa: F401
from . import fused_adam  # noqa: F401
from . import fused_norm_matmul  # noqa: F401
from . import fused_rope_attention  # noqa: F401
from . import int8_matmul  # noqa: F401
from . import paged_attention  # noqa: F401
from . import rms_norm  # noqa: F401
from . import rope  # noqa: F401

# The ONE home of the 2 GiB fp32-score-matrix threshold that decides
# composed-vs-flash attention (BENCH_NOTES "Where the r3->r4 time went"
# and the selection logic both refer here).
from .flash_attention import SCORE_BYTES_THRESHOLD  # noqa: F401
