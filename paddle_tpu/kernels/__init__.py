"""Fused TPU kernels (Pallas) behind the reference's fused-op API names.

Reference parity: paddle/phi/kernels/fusion/gpu/* + flash_attn third-party
lib (unverified, mount empty). Each module provides a Pallas TPU kernel and
a composed-jnp fallback (CPU/CI); call sites pick automatically.
"""
from . import flash_attention  # noqa: F401
from . import fused_adam  # noqa: F401
from . import rms_norm  # noqa: F401
from . import rope  # noqa: F401
