"""Fused rotary position embedding — Pallas TPU kernel.

Reference parity: phi FusedRopeKernel (paddle/phi/kernels/fusion/gpu/
fused_rope_kernel.cu — unverified, mount empty). Layout follows paddle's
fused_rotary_position_embedding: q/k are [B, S, H, D]; rotation pairs are
(x[..., :D/2], x[..., D/2:]) ("neox"/llama style). Backward is the inverse
rotation (same kernel, negated sin) via custom_vjp.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from .autotune import interpret_mode as _interpret


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)  # [1, S_blk, H, D]
    cos = cos_ref[:].astype(jnp.float32)  # [1, S_blk, 1, D/2]
    sin = sin_ref[:].astype(jnp.float32)
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2]
    x2 = x[..., d2:]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    o_ref[:] = jnp.concatenate([o1, o2], axis=-1).astype(o_ref.dtype)


def _seq_block(s, h, d):
    """Largest seq tile whose f32 working set (~7 temporaries of
    [sb, H, D]) stays well inside scoped VMEM: cap one temp at 2MB.
    Pallas TPU needs the last two block dims whole, so tiling is over
    (batch, seq) only."""
    cap = (512 * 1024) // (4 * h * d)
    for b in (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b <= max(cap, 1) and s % b == 0:
            return b
    return 1


def _rope_apply(x, cos, sin):
    b, s, h, d = x.shape
    sb = _seq_block(s, h, d)
    # cos/sin are [1, S, 1, D/2] (one table shared across the batch) or
    # [B, S, 1, D/2] (per-row position gathers — the serving engine's
    # continuous-batching decode, where every batch slot sits at its own
    # position); a shared table always reads batch row 0
    cb = cos.shape[0]
    if cb not in (1, b):
        raise ValueError(
            f"rope cos/sin batch dim must be 1 or {b}, got {cb}"
        )
    tab = (lambda i, k: (i, k, 0, 0)) if cb == b else (
        lambda i, k: (0, k, 0, 0)
    )
    out = pl.pallas_call(
        _rope_kernel,
        grid=(b, s // sb),
        in_specs=[
            pl.BlockSpec((1, sb, h, d), lambda i, k: (i, k, 0, 0)),
            pl.BlockSpec((1, sb, 1, d // 2), tab),
            pl.BlockSpec((1, sb, 1, d // 2), tab),
        ],
        out_specs=pl.BlockSpec((1, sb, h, d), lambda i, k: (i, k, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, s, h, d), x.dtype),
        interpret=_interpret(),
    )(x, cos, sin)
    return out


@jax.custom_vjp
def rope_fused(x, cos, sin):
    """Apply rotary embedding. x [B,S,H,D]; cos/sin [1,S,1,D/2]."""
    return _rope_apply(x, cos, sin)


def _rope_fwd(x, cos, sin):
    return _rope_apply(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    # inverse rotation: rotate by -theta
    return _rope_apply(g, cos, -sin), None, None


rope_fused.defvjp(_rope_fwd, _rope_bwd)


def build_rope_cache(seq_len, head_dim, base=10000.0, dtype=jnp.float32):
    """cos/sin tables [1, S, 1, D/2] (paddle/llama convention)."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [S, D/2]
    return (
        jnp.cos(freqs)[None, :, None, :].astype(dtype),
        jnp.sin(freqs)[None, :, None, :].astype(dtype),
    )
