"""Fused RMSNorm — Pallas TPU kernel with custom VJP.

Reference parity: phi fused RmsNormKernel (paddle/phi/kernels/fusion/gpu/
fused_layernorm_kernel.cu family — unverified, mount empty). One VMEM pass
per row block: mean-of-squares, rsqrt, scale — keeping the activation in
VMEM instead of three HBM round trips. Backward fuses dx and accumulates dw
across row blocks in a resident output block.

Falls back to pallas interpret mode off-TPU (CI) — same code path, host
execution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from .autotune import interpret_mode as _interpret


def _block_rows(n):
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


# ------------------------------------------------------------------ forward


def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = ((x * rstd) * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _rms_fwd(x2d, w, eps):
    n, h = x2d.shape
    br = _block_rows(n)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, w.reshape(1, h))
    return y, rstd


# ----------------------------------------------------------------- backward


def _bwd_kernel(x_ref, w_ref, g_ref, rstd_ref, dx_ref, dw_ref):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    gw = g * w
    # dx = rstd * gw - x * rstd^3 * mean(gw * x)
    m = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx = rstd * gw - x * (rstd * rstd * rstd) * m
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dw accumulates across row blocks into the single resident block
    part = jnp.sum(g * (x * rstd), axis=0, keepdims=True)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[:] = part

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        dw_ref[:] += part


def _rms_bwd(x2d, w, g2d, rstd):
    n, h = x2d.shape
    br = _block_rows(n)
    dx, dw = pl.pallas_call(
        _bwd_kernel,
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, w.reshape(1, h), g2d, rstd)
    return dx, dw.reshape(h)


# -------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_fused(x, w, eps=1e-6):
    """x: [..., H] float; w: [H]. Returns normalized*w, same dtype as x."""
    shape = x.shape
    y, _ = _rms_fwd(x.reshape(-1, shape[-1]), w, eps)
    return y.reshape(shape)


def _vjp_fwd(x, w, eps):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, rstd = _rms_fwd(x2d, w, eps)
    return y.reshape(shape), (x2d, w, rstd, shape)


def _vjp_bwd(eps, res, g):
    x2d, w, rstd, shape = res
    dx, dw = _rms_bwd(x2d, w, g.reshape(x2d.shape).astype(x2d.dtype), rstd)
    return dx.reshape(shape), dw.astype(w.dtype)


rms_norm_fused.defvjp(_vjp_fwd, _vjp_bwd)
