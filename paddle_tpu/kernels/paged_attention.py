"""Paged decode attention — Pallas TPU kernel over a block/page KV pool.

The serving engine's paged decode keeps K/V in a page arena
(``[num_pages, page_size, kvH, D]`` per layer) and addresses each
request's cache through a per-row page table (``[B, pages]`` int32,
page id 0 = the reserved garbage page for unallocated tail entries).
The composed path materializes the gathered cache
(``k_pages[page_table]`` -> ``[B, pages * page_size, kvH, D]``) in HBM
every decode step; this kernel gathers page blocks straight into VMEM
through a scalar-prefetched page table (the classic paged-attention
structure: the table is available before the kernel body runs, so the
BlockSpec index_map can pull the right page per grid step).

Shape contract: q is ``[B, 1, H, D]`` (one decode token per row),
k_pages/v_pages ``[N, page_size, kvH, D]``, page_table ``[B, P]``
int32, pos ``[B]`` int32 (tokens already cached per row; the row
attends cache slots ``[0, pos]`` inclusive — the slot written this
step included).

Bit-exactness discipline (the PR 6 fusion-kernel contract): the kernel
assembles the FULL score row and the FULL gathered V in VMEM scratch
page by page — each score element is one dot over D, and the output is
ONE dot over the assembled S_virtual — the exact-softmax structure
(never online-rescaled), so its math is the composed order: score dot
-> +mask -> fp32 softmax -> value dot. Two reference functions:

- :func:`paged_attention_reference` mirrors the kernel's blocked dots
  op-for-op (pure jnp) and is pinned EXACTLY EQUAL to the kernel in CI
  (the PR 6 parity discipline; the kernel is also invariant in its
  ``block_kvh`` knob).
- :func:`paged_attention_composed` is the gather+SDPA formulation the
  serving engine's DEFAULT paged path runs (op order of ``_sdpa_ref``,
  which the slab engine also decodes through — that identity is what
  keeps default paged token streams exact-equal to ``net.generate``).
  Kernel vs composed agree to float rounding (XLA picks different
  dot microkernels for the two shapes; the parity test bounds it at
  fp32 epsilon), which is why kernel activation stays a measured,
  opt-in decision rather than a default.

Selection is tune-cache OPT-IN (:func:`paged_attention_select`): with
no measured entry for the exact (shape, device) signature the engine
keeps the composed gather path byte-identical; ``tools/kernel_tune.py``
measures and records entries. The tunable is ``block_kvh`` — KV heads
per grid step (``autotune.paged_attention_candidates``).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .autotune import interpret_mode as _interpret


def gather_pages(pages, page_table):
    """``[N, ps, kvH, D]`` arena + ``[B, P]`` table ->
    ``[B, P * ps, kvH, D]`` logical cache (HBM-materializing composed
    gather; the kernel's whole reason to exist is skipping this copy)."""
    b, p = page_table.shape
    n, ps, kvh, d = pages.shape
    return pages[page_table].reshape(b, p * ps, kvh, d)


def gather_pages_dense(pages, page_table, dtype):
    """Composed gather for either arena flavor. Plain arrays: exactly
    :func:`gather_pages` (no cast — the bf16 path stays byte-identical;
    attention upcasts at the matmul). Quantized arenas: gather the int8
    values and their scales, then dequantize-on-gather to the compute
    ``dtype`` — the int8 bytes are what crossed HBM."""
    from ..quantization.kv import dequantize_kv, is_quantized

    if not is_quantized(pages):
        return gather_pages(pages, page_table)
    b, p = page_table.shape
    n, ps, kvh, d = pages.q.shape
    q = pages.q[page_table].reshape(b, p * ps, kvh, d)
    s = pages.scale[page_table].reshape(b, p * ps, kvh)
    return dequantize_kv(q, s, dtype)  # tpu-lint: quant


def _softmax_rows(s):
    """fp32 row softmax, op-for-op ``jax.nn.softmax`` (max-subtract,
    exp, sum-normalize) — masked -inf columns contribute exactly 0."""
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def paged_attention_composed(q, k_pages, v_pages, page_table, pos,
                             scale=None):
    """Composed reference: gather the paged cache and attend — the same
    op order ``nn.functional.scaled_dot_product_attention``'s composed
    body (``_sdpa_ref``) runs for the slab engine, so the paged engine's
    default path and the slab engine round identically.

    q ``[B, 1, H, D]``; returns ``[B, 1, H, D]`` in q's dtype.
    Quantized (int8) arenas dequantize-on-gather to q's dtype first —
    the op order the engine's default int8 paged path runs."""
    b, sq, h, d = (int(x) for x in q.shape)
    kvh = int(k_pages.shape[2])
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    kk = gather_pages_dense(k_pages, page_table, q.dtype)
    vv = gather_pages_dense(v_pages, page_table, q.dtype)
    if kvh != h:
        rep = h // kvh
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
    s_virt = int(kk.shape[1])
    # [B, H, sq, S_virt] score + position mask, then _sdpa_ref's order
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(kk, 1, 2)
    vt = jnp.swapaxes(vv, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    valid = jnp.arange(s_virt)[None, None, None, :] \
        <= pos[:, None, None, None]
    s = s + jnp.where(valid, 0.0, -jnp.inf)
    p = _softmax_rows(s.astype(jnp.float32)).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


def paged_attention_reference(q, k_pages, v_pages, page_table, pos,
                              scale=None):
    """Pure-jnp mirror of the kernel's blocked math (per-row, per
    kv-head, per-page dots assembled into a full score row + gathered V,
    ONE softmax, ONE value dot). Pinned bit-identical to
    :func:`paged_attention_fused` in CI. Loop-based — a verification
    reference, not a serving path. Quantized arenas dequantize each
    page block to fp32 (value * scale) exactly as the kernel does in
    VMEM, so the bit-exact pin covers the int8 flavor too."""
    from ..quantization.kv import is_quantized

    b, sq, h, d = (int(x) for x in q.shape)
    quant = is_quantized(k_pages)

    def _page(pages_arr, bi, p, j):
        if is_quantized(pages_arr):
            return (
                pages_arr.q[page_table[bi, p], :, j].astype(jnp.float32)
                * pages_arr.scale[page_table[bi, p], :, j][:, None]
            )  # tpu-lint: quant
        return pages_arr[page_table[bi, p], :, j].astype(jnp.float32)

    kvh = int((k_pages.q if quant else k_pages).shape[2])
    ps = int((k_pages.q if quant else k_pages).shape[1])
    pages = int(page_table.shape[1])
    group = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s_virt = pages * ps
    rows = []
    for bi in range(b):
        heads = []
        for j in range(kvh):
            qg = q[bi, 0].reshape(kvh, group, d)[j].astype(jnp.float32)
            srow, vrow = [], []
            for p in range(pages):
                kpage = _page(k_pages, bi, p, j)
                kg = jnp.repeat(kpage[:, None, :], group, axis=1)
                s = jax.lax.dot_general(
                    qg, jnp.swapaxes(kg, 0, 1),
                    (((1,), (2,)), ((0,), (0,))),
                ) * scale
                srow.append(s)
                vpage = jnp.repeat(
                    _page(v_pages, bi, p, j)[:, None, :], group, axis=1,
                )
                vrow.append(vpage.reshape(ps, -1))
            sfull = jnp.concatenate(srow, axis=1)         # [G, S_virt]
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (group, s_virt), 1
            )
            sm = sfull + jnp.where(cols <= pos[bi], 0.0, -jnp.inf)
            prob = _softmax_rows(sm).astype(q.dtype).astype(jnp.float32)
            vall = jnp.concatenate(vrow, axis=0).reshape(s_virt, group,
                                                         d)
            o = jax.lax.dot_general(
                prob, jnp.swapaxes(vall, 0, 1),
                (((1,), (1,)), ((0,), (0,))),
            )
            heads.append(o)
        rows.append(jnp.concatenate(heads, axis=0))
    return jnp.stack(rows)[:, None].astype(q.dtype)


def _paged_body(table_ref, pos_ref, q_ref, k, v, o_ref, s_scratch,
                v_scratch, *, scale, page_size, pages, group,
                out_dtype):
    """The SHARED kernel body both arena flavors run after their load
    epilogue: grid step p assembles page p's score columns and V rows
    into scratch; the LAST page step softmaxes the full row and emits
    the output block. ``k``/``v`` arrive as fp32 ``[ps, bkvh, D]`` —
    already dequantized by the caller — so the masking/softmax/emit
    math has exactly ONE home and the two flavors can never round
    apart.

    q_ref ``[1, G, D]`` (G = block_kvh * group query heads)."""
    b = pl.program_id(0)
    p = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                 # [G, D]
    # GQA: repeat the page's KV heads up to the query-head group, in
    # kv-head-major order to match jnp.repeat(kk, rep, axis=2)
    k = jnp.repeat(k, group, axis=1)                    # [ps, G, D]
    v = jnp.repeat(v, group, axis=1)
    # score columns for this page: one dot over D per element — the
    # same dot_general contraction the composed einsum lowers to
    s = jax.lax.dot_general(
        q, jnp.swapaxes(k, 0, 1),                       # [G, ps, D]
        (((1,), (2,)), ((0,), (0,))),                   # d-with-d, G batched
    ) * scale                                           # [G, ps]
    s_scratch[:, pl.ds(p * page_size, page_size)] = s
    v_scratch[pl.ds(p * page_size, page_size), :] = \
        v.reshape(page_size, -1)                        # [ps, G*D]

    @pl.when(p == pages - 1)
    def _emit():
        s_virt = pages * page_size
        g = q.shape[0]
        d = q.shape[1]
        cols = jax.lax.broadcasted_iota(jnp.int32, (g, s_virt), 1)
        mask = jnp.where(cols <= pos_ref[b], 0.0, -jnp.inf)
        sm = s_scratch[...] + mask
        prob = _softmax_rows(sm).astype(out_dtype).astype(jnp.float32)
        # ONE dot over the assembled S_virt — same reduction the
        # composed value einsum performs
        vall = v_scratch[...].reshape(s_virt, g, d)     # [S, G, D]
        out = jax.lax.dot_general(
            prob, jnp.swapaxes(vall, 0, 1),             # [G, S, D]
            (((1,), (1,)), ((0,), (0,))),
        )                                               # [G, D]
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _paged_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  s_scratch, v_scratch, *, scale, page_size, pages,
                  group, out_dtype):
    """Float-arena flavor: load epilogue is a plain fp32 upcast of the
    table-indexed page block; everything else is :func:`_paged_body`.

    k_ref/v_ref ``[1, ps, bkvh, D]`` — one table-indexed page block."""
    _paged_body(
        table_ref, pos_ref, q_ref,
        k_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
        o_ref, s_scratch, v_scratch, scale=scale, page_size=page_size,
        pages=pages, group=group, out_dtype=out_dtype,
    )


def _paged_kernel_quant(table_ref, pos_ref, q_ref, k_ref, ks_ref, v_ref,
                        vs_ref, o_ref, s_scratch, v_scratch, *, scale,
                        page_size, pages, group, out_dtype):
    """Int8-arena flavor: the page block arrives as int8 values +
    per-(slot, kv-head) fp32 scales and the load epilogue dequantizes
    in VMEM (value * scale — the exact op order the blocked reference
    mirrors), so only the narrow bytes ever cross HBM. Everything past
    the load is the shared :func:`_paged_body`."""
    # dequant-on-gather, in VMEM: [ps, bkvh, D] fp32  # tpu-lint: quant
    k = k_ref[0].astype(jnp.float32) * ks_ref[0][..., None]
    v = v_ref[0].astype(jnp.float32) * vs_ref[0][..., None]
    _paged_body(
        table_ref, pos_ref, q_ref, k, v, o_ref, s_scratch, v_scratch,
        scale=scale, page_size=page_size, pages=pages, group=group,
        out_dtype=out_dtype,
    )


def paged_attention_fused(q, k_pages, v_pages, page_table, pos,
                          scale=None, block_kvh=1):
    """Pallas paged decode attention. Shapes per the module docstring;
    ``block_kvh`` KV heads are processed per grid step (tuned knob).
    ``k_pages``/``v_pages`` may be int8 ``QuantizedKV`` arenas — the
    kernel then streams int8 pages + scales and dequantizes in VMEM."""
    from jax.experimental.pallas import tpu as pltpu

    from ..quantization.kv import is_quantized

    quant = is_quantized(k_pages)
    if quant != is_quantized(v_pages):
        raise ValueError("k_pages and v_pages must share quantization")
    k_arr = k_pages.q if quant else k_pages
    b, sq, h, d = (int(x) for x in q.shape)
    if sq != 1:
        raise ValueError(
            f"paged attention is the decode step: one token per row "
            f"(q [B, 1, H, D]), got S={sq}"
        )
    n, ps, kvh, dk = (int(x) for x in k_arr.shape)
    if dk != d:
        raise ValueError(f"head_dim mismatch: q D={d}, pages D={dk}")
    if h % kvh:
        raise ValueError(f"H={h} not a multiple of kvH={kvh}")
    if kvh % int(block_kvh):
        raise ValueError(f"block_kvh={block_kvh} does not divide "
                         f"kvH={kvh}")
    pages = int(page_table.shape[1])
    group = h // kvh
    bkvh = int(block_kvh)
    g = bkvh * group                 # query heads per grid step
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s_virt = pages * ps
    # q in kv-head-major layout so a kv-head block's query heads are
    # contiguous: [B, kvH, group, D] -> [B, kvH/bkvh, g, D]
    qh = q.reshape(b, 1, kvh, group, d)[:, 0].reshape(b, kvh // bkvh,
                                                      g, d)
    table = page_table.astype(jnp.int32)
    posv = pos.astype(jnp.int32)

    page_spec = pl.BlockSpec(
        (1, ps, bkvh, d), lambda i, j, p, tbl, ps_: (tbl[i, p], 0, j, 0)
    )
    scale_spec = pl.BlockSpec(
        (1, ps, bkvh), lambda i, j, p, tbl, ps_: (tbl[i, p], 0, j)
    )
    q_spec = pl.BlockSpec((1, 1, g, d),
                          lambda i, j, p, tbl, ps_: (i, j, 0, 0))
    if quant:
        in_specs = [q_spec, page_spec, scale_spec, page_spec, scale_spec]
        operands = (table, posv, qh, k_pages.q, k_pages.scale,
                    v_pages.q, v_pages.scale)
        kernel = _paged_kernel_quant
    else:
        in_specs = [q_spec, page_spec, page_spec]
        operands = (table, posv, qh, k_pages, v_pages)
        kernel = _paged_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,       # (page_table, pos)
        grid=(b, kvh // bkvh, pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda i, j, p, tbl, ps_: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, s_virt), jnp.float32),
            pltpu.VMEM((s_virt, g * d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            kernel, scale=float(scale), page_size=ps,
            pages=pages, group=group, out_dtype=q.dtype,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh // bkvh, g, d), q.dtype),
        interpret=_interpret(),
    )(*operands)
    # [B, kvH/bkvh, g, D] -> [B, 1, H, D]
    return out.reshape(b, 1, h, d)


def paged_attention_select(b, pages, page_size, h, kvh, d,
                           quantized=False):
    """Tune-cache OPT-IN selection: the kernel's config when a measured
    entry exists for this exact shape on this device, else None (the
    engine keeps the composed gather path byte-identical). Stale cached
    configs are counted, one-shot-warned fallbacks; a measured
    composed-wins verdict is honored as a policy decision. Int8 arenas
    tune under their own signature (``..._q8``) — the int8 kernel's
    bandwidth/compute profile is different hardware behavior, so a bf16
    measurement must never activate the quantized kernel untested."""
    from . import autotune

    sig = autotune.paged_attention_sig(b, pages, page_size, h, kvh, d,
                                       quant=quantized)
    entry = autotune.lookup_entry("paged_attention", sig)
    if entry is None:
        return None
    cfg = dict(entry["config"])
    if not autotune.paged_attention_config_legal(kvh, cfg):
        autotune.note_fallback("paged_attention", sig, "stale-config",
                               detail=f"cached {cfg} illegal for "
                                      f"kvH={kvh}")
        return None
    if entry.get("fused_beats_composed") is False:
        autotune.note_selection("paged_attention", "composed:measured")
        return None
    autotune.note_selection("paged_attention", "fused:cached")
    return cfg


def _apply_fn(qv, kv, vv, tbl, posv, *, scale, block_kvh):
    return paged_attention_fused(qv, kv, vv, tbl, posv, scale=scale,
                                 block_kvh=block_kvh)


def paged_attention_apply(q, k_pages, v_pages, page_table, pos, *,
                          config, scale=None):
    """Tensor-level entry for model code (decode is a no-grad path, so
    no VJP is registered — ``nondiff`` keeps the tape clean)."""
    from ..core import dispatch

    return dispatch.apply(
        "paged_attention", _apply_fn,
        (q, k_pages, v_pages, page_table, pos),
        {"scale": scale,
         "block_kvh": int(config.get("block_kvh", 1))},
        nondiff=True,
    )


