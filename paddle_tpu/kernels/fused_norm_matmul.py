"""Fused RMSNorm + matmul epilogue — Pallas TPU kernel.

The model head runs ``rms_norm(h) @ W_lm`` (final norm + lm_head) in
both the train step and the serving/generation decode step. Unfused,
the normalized activation makes an HBM round trip between the two ops;
this kernel normalizes each row block in VMEM and feeds it straight
into its slice of the matmul — the normalized tensor never exists in
HBM. Grid tiles (row-block x col-block) of the output; the cheap norm
is recomputed per column block (O(rows*H) VPU work) to keep every grid
step independent.

Block sizes (block_rows, block_cols) are the tuned knobs
(``autotune.norm_matmul_candidates``). Backward runs through the
composed reference's VJP (same pattern as fused_rope_attention), so the
train step can select the fused forward too.

Selection is tune-cache OPT-IN (:func:`head_fusion_select`): with no
cache entry, call sites keep today's unfused path byte-identical.

Falls back to pallas interpret mode off-TPU (CI) — same code path, host
execution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from .autotune import interpret_mode as _interpret


def _normed_rows(x, w, eps):
    """fp32 RMSNorm of a row block, cast back to the activation dtype —
    op-for-op the math of kernels/rms_norm.py's forward (and the
    composed reference below; bit-exact parity is pinned in CI)."""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    return ((xf * rstd) * w.astype(jnp.float32)).astype(x.dtype)


def _fused_kernel(x_ref, w_ref, m_ref, o_ref, *, eps):
    y = _normed_rows(x_ref[:], w_ref[:], eps)   # [br, H]
    o_ref[:] = jnp.dot(y, m_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm_matmul(x2d, w, wm, eps, block_rows, block_cols):
    n, h = x2d.shape
    n_out = wm.shape[1]
    out_dtype = jnp.promote_types(x2d.dtype, wm.dtype)
    return pl.pallas_call(
        functools.partial(_fused_kernel, eps=eps),
        grid=(n // block_rows, n_out // block_cols),
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i, j: (i, 0)),
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),
            pl.BlockSpec((h, block_cols), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_rows, block_cols),
                               lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, n_out), out_dtype),
        interpret=_interpret(),
    )(x2d, w.reshape(1, h), wm)


def _composed_2d(x2d, w, wm, eps):
    return jnp.dot(_normed_rows(x2d, w.reshape(1, -1), eps), wm)


def _fwd(x2d, w, wm, eps, block_rows, block_cols):
    return (
        _norm_matmul(x2d, w, wm, eps, block_rows, block_cols),
        (x2d, w, wm),
    )


def _bwd(eps, block_rows, block_cols, res, g):
    x2d, w, wm = res
    _, vjp = jax.vjp(
        lambda xv, wv, mv: _composed_2d(xv, wv, mv, eps), x2d, w, wm
    )
    return vjp(g)


_norm_matmul.defvjp(_fwd, _bwd)


def _resolve_blocks(rows, n_out, block_rows, block_cols):
    from . import autotune

    if block_rows is None or block_cols is None:
        cands = autotune.norm_matmul_candidates(rows, n_out)
        if not cands:
            raise ValueError(
                f"rows={rows} n_out={n_out} have no legal block config"
            )
        block_rows = block_rows or cands[0]["block_rows"]
        block_cols = block_cols or cands[0]["block_cols"]
    if rows % int(block_rows) or n_out % int(block_cols):
        raise ValueError(
            f"blocks ({block_rows}, {block_cols}) do not divide "
            f"({rows}, {n_out})"
        )
    return int(block_rows), int(block_cols)


def rms_norm_matmul(x, w, wm, eps=1e-6, block_rows=None, block_cols=None):
    """``rms_norm(x, w) @ wm`` in one kernel. x: [..., H]; w: [H] norm
    weight; wm: [H, N] matmul weight (paddle Linear layout). Returns
    [..., N]."""
    shape = x.shape
    h = int(shape[-1])
    x2d = x.reshape(-1, h)
    rows, n_out = int(x2d.shape[0]), int(wm.shape[1])
    br, bc = _resolve_blocks(rows, n_out, block_rows, block_cols)
    out = _norm_matmul(x2d, w, wm, float(eps), br, bc)
    return out.reshape(tuple(shape[:-1]) + (n_out,))


def rms_norm_matmul_composed(x, w, wm, eps=1e-6):
    """Composed reference (plain jnp, XLA-fused): normalize then matmul
    — op-for-op the math of the fused kernel, without the fusion. The
    parity tests pin the two bit-exact; the fused backward runs through
    this function's VJP."""
    shape = x.shape
    x2d = x.reshape(-1, int(shape[-1]))
    out = _composed_2d(x2d, w, wm, float(eps))
    return out.reshape(tuple(shape[:-1]) + (int(wm.shape[1]),))


def head_fusion_select(rows, hidden, n_out):
    """Tune-cache OPT-IN selection for the norm+matmul head: the fused
    config when a measured entry exists for this exact shape on this
    device, else None (call sites keep the unfused path —
    byte-identical to the pre-autotuner behavior)."""
    from . import autotune

    sig = autotune.norm_matmul_sig(rows, hidden, n_out)
    entry = autotune.lookup_entry("rms_norm_matmul", sig)
    if entry is None:
        return None
    cfg = dict(entry["config"])
    if not autotune.norm_matmul_config_legal(rows, n_out, cfg):
        autotune.note_fallback(
            "rms_norm_matmul", sig, "stale-config",
            detail=f"cached {cfg} illegal for ({rows}, {n_out})",
        )
        return None
    if entry.get("fused_beats_composed") is False:
        # the tuner measured composed FASTER for this exact shape on
        # this device — a measured policy decision, not a fallback
        autotune.note_selection("rms_norm_matmul", "composed:measured")
        return None
    autotune.note_selection("rms_norm_matmul", "fused:cached")
    return cfg


def _apply_fn(xv, wv, mv, *, eps, block_rows, block_cols):
    return rms_norm_matmul(xv, wv, mv, eps=eps, block_rows=block_rows,
                           block_cols=block_cols)


def rms_norm_matmul_apply(x, w, wm, *, eps=1e-6, block_rows=None,
                          block_cols=None):
    """Tensor-level entry (grad-recording via core.dispatch) for model
    code."""
    from ..core import dispatch

    return dispatch.apply(
        "rms_norm_matmul", _apply_fn, (x, w, wm),
        {"eps": float(eps), "block_rows": block_rows,
         "block_cols": block_cols},
    )
