"""Weight-only int8 matmul with in-kernel dequant epilogue — Pallas TPU.

The serving decode step is weight-bandwidth bound: every projection
streams its full weight matrix from HBM to multiply one token per
resident row. ``quantize_for_serving`` stores those weights as int8
values + per-output-channel fp32 scales (quantization/serving.py);
this kernel consumes them directly — the int8 block is dequantized in
VMEM as part of the weight load's epilogue and fed straight into its
output tile's matmul, so the wide weight NEVER exists in HBM and the
bytes crossing the HBM bus drop ~2x vs bf16 (~4x vs fp32). This is the
FlashFuser move (PAPERS.md) applied to dequantization: fold the
producer into the consumer instead of materializing the intermediate.

Bit-exactness discipline (the PR 6 fusion-kernel contract): the kernel
tile computes ``x_block @ ((w_q_block * scale_block) cast to x.dtype)``
— elementwise dequant then ONE dot over the full contraction dim, the
exact op order of :func:`int8_matmul_composed` — so fused and composed
are pinned EQUAL in CI (fwd only: this is the no-grad decode path).

Selection is tune-cache OPT-IN (:func:`int8_matmul_select`), same
discipline as the other fused kernels: no measured entry for the exact
(shape, device) -> the composed dequant->matmul runs byte-identical;
``fused_beats_composed=False`` entries are honored as measured policy;
stale/illegal cached configs are counted one-shot-warned fallbacks.
Block sizes (block_rows, block_cols) are the tuned knobs
(``autotune.int8_matmul_candidates``).

Falls back to pallas interpret mode off-TPU (CI) — same code path,
host execution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .autotune import interpret_mode as _interpret


def quantize_weight_with_scales(w, scale):
    """The ONE home of the int8 weight rounding rule: float ``[in,
    out]`` weight + per-out-channel fp32 ``[out]`` scales -> int8
    values. Fresh-absmax and PTQ-calibrated callers both round here,
    so the two deploy paths can never drift apart."""
    wf = jnp.asarray(w).astype(jnp.float32)
    s = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-8)
    q = jnp.clip(
        jnp.round(wf / s[None, :]), -127, 127
    ).astype(jnp.int8)  # tpu-lint: quant
    return q, s


def quantize_weight(w):
    """Float ``[in, out]`` weight -> (int8 values, fp32 per-out-channel
    scales ``[out]``). Symmetric absmax over the contraction axis."""
    wf = jnp.asarray(w).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)
    return quantize_weight_with_scales(wf, absmax / 127.0)


def _dequant(w_q, scale, dtype):
    """The shared dequant op order: int8 -> fp32 * scale -> compute
    dtype. ONE home so kernel and composed can never round apart."""
    return (
        w_q.astype(jnp.float32) * scale
    ).astype(dtype)  # tpu-lint: quant


def _int8_kernel(x_ref, w_ref, s_ref, o_ref, *, out_dtype):
    w = _dequant(w_ref[:], s_ref[:], x_ref.dtype)   # [H, bc] in VMEM
    o_ref[:] = jnp.dot(x_ref[:], w).astype(out_dtype)


def int8_matmul(x, w_q, scale, block_rows=None, block_cols=None):
    """``x @ dequant(w_q, scale)`` in one kernel. x: [..., H] float;
    w_q: int8 [H, N]; scale: fp32 [N]. Returns [..., N] in x's dtype."""
    shape = x.shape
    h = int(shape[-1])
    x2d = x.reshape(-1, h)
    rows, n_out = int(x2d.shape[0]), int(w_q.shape[1])
    br, bc = _resolve_blocks(rows, n_out, block_rows, block_cols)
    out = pl.pallas_call(
        functools.partial(_int8_kernel, out_dtype=x2d.dtype),
        grid=(rows // br, n_out // bc),
        in_specs=[
            pl.BlockSpec((br, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bc), lambda i, j: (0, j)),
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((br, bc), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows, n_out), x2d.dtype),
        interpret=_interpret(),
    )(x2d, w_q, scale.reshape(1, n_out).astype(jnp.float32))
    return out.reshape(tuple(shape[:-1]) + (n_out,))


def int8_matmul_composed(x, w_q, scale):
    """Composed reference: dequantize the whole weight, then matmul —
    op-for-op the kernel's math without the fusion (the wide weight
    materializes in HBM; skipping that copy is the kernel's win). The
    parity tests pin the two equal; untuned call sites run this."""
    shape = x.shape
    h = int(shape[-1])
    x2d = x.reshape(-1, h)
    n_out = int(w_q.shape[1])
    w = _dequant(w_q, scale.reshape(1, n_out).astype(jnp.float32),
                 x2d.dtype)
    return jnp.dot(x2d, w).reshape(tuple(shape[:-1]) + (n_out,))


def _resolve_blocks(rows, n_out, block_rows, block_cols):
    from . import autotune

    if block_rows is None or block_cols is None:
        cands = autotune.int8_matmul_candidates(rows, n_out)
        if not cands:
            raise ValueError(
                f"rows={rows} n_out={n_out} have no legal block config"
            )
        block_rows = block_rows or cands[0]["block_rows"]
        block_cols = block_cols or cands[0]["block_cols"]
    if rows % int(block_rows) or n_out % int(block_cols):
        raise ValueError(
            f"blocks ({block_rows}, {block_cols}) do not divide "
            f"({rows}, {n_out})"
        )
    return int(block_rows), int(block_cols)


def int8_matmul_select(rows, hidden, n_out):
    """Tune-cache OPT-IN selection: the fused kernel's config when a
    measured entry exists for this exact shape on this device, else
    None (call sites keep the composed dequant->matmul)."""
    from . import autotune

    sig = autotune.int8_matmul_sig(rows, hidden, n_out)
    entry = autotune.lookup_entry("int8_matmul", sig)
    if entry is None:
        return None
    cfg = dict(entry["config"])
    if not autotune.int8_matmul_config_legal(rows, n_out, cfg):
        autotune.note_fallback(
            "int8_matmul", sig, "stale-config",
            detail=f"cached {cfg} illegal for ({rows}, {n_out})",
        )
        return None
    if entry.get("fused_beats_composed") is False:
        autotune.note_selection("int8_matmul", "composed:measured")
        return None
    autotune.note_selection("int8_matmul", "fused:cached")
    return cfg


def _apply_fused(xv, wqv, sv, *, block_rows, block_cols):
    return int8_matmul(xv, wqv, sv, block_rows=block_rows,
                       block_cols=block_cols)


def _apply_composed(xv, wqv, sv):
    return int8_matmul_composed(xv, wqv, sv)


def int8_matmul_apply(x, w_q, scale, *, config=None):
    """Tensor-level entry for model code. ``config`` (from
    :func:`int8_matmul_select`) activates the fused kernel; None runs
    the composed path. Weight-only decode is a no-grad path — the op
    registers nondiff (train-time quantization goes through the QAT
    fake-quant STE instead)."""
    from ..core import dispatch

    if config is not None:
        return dispatch.apply(
            "int8_matmul", _apply_fused, (x, w_q, scale),
            {"block_rows": int(config["block_rows"]),
             "block_cols": int(config["block_cols"])},
            nondiff=True,
        )
    return dispatch.apply(
        "int8_matmul", _apply_composed, (x, w_q, scale), nondiff=True,
    )
