"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (paddle/phi/kernels/gpu/
flash_attn_kernel.cu wrapping the flash-attention lib — unverified, mount
empty). On TPU the equivalent is a Pallas blockwise-softmax kernel; jax
ships a production-quality one (jax.experimental.pallas.ops.tpu.flash_attention)
which we use when shapes allow, with a composed-jnp fallback otherwise.
Layout contract matches paddle: q/k/v are [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _composed(q, k, v, *, causal, scale):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=1)
def _pallas_fa():
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        return flash_attention
    except Exception:
        return None


# Round-5 v5e ablation (fwd+bwd, causal, B=4 H=16 D=128 — the flagship
# head geometry; interleaved A/B medians, BENCH_NOTES for the full
# table). The round-3 "pallas always loses on time" result was an
# artifact of the kernel's DEFAULT block sizes (8x128 q-blocks); with
# blocks tuned for v5e (block_q=512, block_k_major=1024, block_k=512 —
# and the same for both backward passes) the causal kernel's
# block-skipping of upper-triangle work wins outright once S is large
# enough for the skipped half to dominate:
#   S=1024: composed 23.9ms  pallas-tuned 24.2ms   (parity, within noise)
#   S=2048: composed 29.6ms  pallas-tuned 27.7ms   (pallas)
#   S=4096: composed 30.7ms  pallas-tuned 20.1ms   (pallas, 1.5x)
#   (default blocks for reference: 10.0/23.9/78.3ms at 1024/2048/4096)
# Selection: the tuned pallas kernel for causal attention from S>=2048
# (the isolated A/B is parity at 1024, but inside the full compiled
# flagship step composed still edges it there — 64.2% vs 62.6% MFU
# measured — so the threshold sits where the win is real), and for ANY
# shape whose fp32 score matrix exceeds SCORE_BYTES_THRESHOLD (composed
# materializes O(B*H*S^2) scores; flash is O(S)). Non-causal below the
# threshold stays composed — there is no triangle to skip and XLA's
# fused attention is at parity or better there.
#
# Which BLOCK SIZES the pallas path uses is now a tune-cache lookup
# (kernels/autotune.py): a measured entry for (shape, device) wins;
# otherwise the seeded v5e triple below (clamped for short seqs); and
# when the seed is not legal for the shape, the divisibility-aware
# candidate generator supplies a legal config instead of silently
# dropping to composed.

# The 2 GiB fp32-score-matrix threshold. ONE home (exported from
# kernels/__init__.py) — BENCH_NOTES prose and the selection logic both
# refer to this constant.
SCORE_BYTES_THRESHOLD = 2 << 30
_PALLAS_CAUSAL_MIN_SEQ = 2048

# the hand-measured v5e optimum (BENCH_NOTES r5) — the seeded default
# every shape gets until a tune-cache entry supersedes it
SEED_BLOCKS = {"block_q": 512, "block_k_major": 1024, "block_k": 512}


def _seed_config(sq, sk):
    """The seeded v5e triple, clamped for short sequences."""
    return {
        "block_q": min(SEED_BLOCKS["block_q"], sq),
        "block_k_major": min(SEED_BLOCKS["block_k_major"], sk),
        "block_k": min(SEED_BLOCKS["block_k"], sk),
    }


def _resolve_config(sq, sk, b=None, h=None, d=None, causal=True):
    """Block config for (sq, sk) and where it came from:
    ``(config, source, fused_wins)`` with source one of "cached"
    (tune-cache entry for the full shape signature), "seed" (the v5e
    default, clamped), "generated" (divisibility-aware candidate —
    legal but unmeasured), or ``(None, "none", None)`` when no legal
    config exists (sq/sk lack an MXU-friendly divisor).
    ``fused_wins`` is the tuner's measured fused-vs-composed verdict
    for a cached entry (None when absent/unmeasured — the seeded v5e
    entries are hand-validated wins)."""
    from . import autotune

    if b is not None and h is not None and d is not None:
        sig = autotune.flash_sig(b, sq, sk, h, d, causal)
        entry = autotune.lookup_entry("flash_attention", sig)
        if entry is not None:
            cached = dict(entry["config"])
            if autotune.flash_config_legal(sq, sk, cached):
                return cached, "cached", entry.get("fused_beats_composed")
            # a stale/illegal cached entry must be as visible here as it
            # is for the fusion kernels (metric + one-shot warning)
            autotune.note_fallback(
                "flash_attention", sig, "stale-config",
                detail=f"cached {cached} illegal for sq={sq} sk={sk}",
            )
    seed = _seed_config(sq, sk)
    if autotune.flash_config_legal(sq, sk, seed):
        return seed, "seed", None
    cands = autotune.flash_block_candidates(sq, sk)
    if cands:
        return cands[0], "generated", None
    return None, "none", None


def _tuned_block_sizes(sq, sk, b=None, h=None, d=None, causal=True,
                       config=None):
    """BlockSizes for the stock kernel: the tune-cache entry when one
    exists for the full (b, sq, sk, h, d, causal) signature, else the
    seeded v5e triple (clamped), else a generated legal config."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
    )

    cfg = config or _resolve_config(sq, sk, b=b, h=h, d=d,
                                    causal=causal)[0]  # (cfg, src, wins)
    if cfg is None:
        cfg = _seed_config(sq, sk)  # caller should have checked legality
    bq, bkm, bk = cfg["block_q"], cfg["block_k_major"], cfg["block_k"]
    return BlockSizes(
        block_q=bq, block_k_major=bkm, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkm, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bkm, block_k_dq=bk, block_q_dq=bq,
    )


def _select(q, k, v, causal):
    """Full selection decision: ``(use_pallas, config, reason)``.

    ``reason`` explains composed picks: policy reasons (the composed
    path is genuinely preferred) are silent; capability fallbacks (the
    pallas path is WANTED but cannot run) publish a fallback metric, a
    one-shot warning, and a flight-recorder event via
    ``autotune.note_fallback`` — a non-divisible long-context shape no
    longer loses its 1.5x win silently."""
    from . import autotune

    b, sq, h, d = (int(s) for s in q.shape)
    sk = int(k.shape[1])
    if all(dev.platform == "cpu" for dev in jax.devices()):
        return False, None, "policy:cpu"
    score_bytes = 4 * b * h * sq * sk  # fp32 softmax intermediate
    wanted = (
        # sq == sk required: for cross-length causal attention the
        # pallas kernel's top-left-aligned causal mask disagrees with
        # composed's bottom-right-aligned one (tril k=sk-sq)
        (causal and sq == sk and sk >= _PALLAS_CAUSAL_MIN_SEQ)
        or (not causal and score_bytes > SCORE_BYTES_THRESHOLD)
        or (causal and sq == sk and score_bytes > SCORE_BYTES_THRESHOLD)
    )
    if not wanted:
        if causal and sq != sk and (
                sk >= _PALLAS_CAUSAL_MIN_SEQ
                or score_bytes > SCORE_BYTES_THRESHOLD):
            # cross-length causal is a semantic exclusion, but at these
            # sizes the composed path is paying the full O(S^2) bill —
            # surface it (it is the paged/decode shape to fix next)
            return False, None, "policy:cross-length-causal"
        return False, None, "policy:below-threshold"
    sig = autotune.flash_sig(b, sq, sk, h, d, causal)
    if _pallas_fa() is None:
        autotune.note_fallback("flash_attention", sig,
                               "kernel-unavailable")
        return False, None, "fallback:kernel-unavailable"
    if int(v.shape[1]) != sk:
        autotune.note_fallback("flash_attention", sig, "kv-length-mismatch")
        return False, None, "fallback:kv-length-mismatch"
    if d not in (64, 128, 256):
        autotune.note_fallback("flash_attention", sig, "head-dim",
                               detail=f"d={d} not in (64, 128, 256)")
        return False, None, "fallback:head-dim"
    cfg, source, fused_wins = _resolve_config(sq, sk, b=b, h=h, d=d,
                                              causal=causal)
    if cfg is None:
        autotune.note_fallback(
            "flash_attention", sig, "indivisible",
            detail=f"sq={sq} sk={sk} have no legal block config",
        )
        return False, None, "fallback:indivisible"
    if (source == "cached" and fused_wins is False
            and score_bytes <= SCORE_BYTES_THRESHOLD):
        # the tuner measured composed FASTER than the best pallas
        # candidate for this exact shape — honor the measurement in the
        # time regime (a measured policy decision, not a fallback). In
        # the memory regime pallas still wins by not materializing the
        # O(S^2) scores, whatever the isolated timing said.
        return False, None, "policy:measured-composed-wins"
    if source == "generated" and score_bytes <= SCORE_BYTES_THRESHOLD:
        # a generated config is legal but UNMEASURED, and its blocks are
        # necessarily smaller than the seed's (the seed was illegal) —
        # BENCH_NOTES measured small/default blocks up to 2.5x slower
        # than composed, so in the time-win regime composed is the safe
        # choice until the tuner measures this shape. In the memory
        # regime (score matrix > SCORE_BYTES_THRESHOLD) any legal
        # pallas config beats materializing the O(S^2) scores.
        autotune.note_fallback(
            "flash_attention", sig, "untuned-config",
            detail=f"generated {cfg} is unmeasured; composed kept",
        )
        return False, None, "fallback:untuned-config"
    return True, cfg, f"pallas:{source}"


def _pallas_ok(q, k, v, causal):
    return _select(q, k, v, causal)[0]


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """q/k/v: [B, S, H, D] -> [B, S, H, D].

    q and k/v may arrive in different dtypes (bf16 KV caches from the
    serving pool / ``generate(cache_dtype=...)``, or fp32 caches under
    a bf16-activation model): align everything to the PROMOTED dtype —
    always widening, never rounding a wider cache down — so the Pallas
    kernel sees uniform operands and the composed path gets exactly the
    promotion XLA would insert."""
    ct = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype), v.dtype)
    if q.dtype != ct:
        q = q.astype(ct)
    if k.dtype != ct:
        k = k.astype(ct)
    if v.dtype != ct:
        v = v.astype(ct)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    use_pallas, cfg, reason = _select(q, k, v, causal)
    from . import autotune

    # the full reason is the path label ("pallas:seed", "policy:
    # cross-length-causal", "fallback:indivisible", ...): composed picks
    # stay distinguishable by WHY — e.g. the cross-length causal decode
    # shape paying the O(S^2) bill is its own series, not an anonymous
    # "composed"
    autotune.note_selection("flash_attention", reason)
    if use_pallas:
        fa = _pallas_fa()
        # pallas kernel layout: [B, H, S, D]
        out = fa(
            jnp.swapaxes(q, 1, 2),
            jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2),
            causal=causal,
            sm_scale=scale,
            block_sizes=_tuned_block_sizes(
                int(q.shape[1]), int(k.shape[1]), config=cfg
            ),
        )
        return jnp.swapaxes(out, 1, 2)
    return _composed(q, k, v, causal=causal, scale=scale)
