"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (paddle/phi/kernels/gpu/
flash_attn_kernel.cu wrapping the flash-attention lib — unverified, mount
empty). On TPU the equivalent is a Pallas blockwise-softmax kernel; jax
ships a production-quality one (jax.experimental.pallas.ops.tpu.flash_attention)
which we use when shapes allow, with a composed-jnp fallback otherwise.
Layout contract matches paddle: q/k/v are [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _composed(q, k, v, *, causal, scale):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=1)
def _pallas_fa():
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        return flash_attention
    except Exception:
        return None


# Round-5 v5e ablation (fwd+bwd, causal, B=4 H=16 D=128 — the flagship
# head geometry; interleaved A/B medians, BENCH_NOTES for the full
# table). The round-3 "pallas always loses on time" result was an
# artifact of the kernel's DEFAULT block sizes (8x128 q-blocks); with
# blocks tuned for v5e (block_q=512, block_k_major=1024, block_k=512 —
# and the same for both backward passes) the causal kernel's
# block-skipping of upper-triangle work wins outright once S is large
# enough for the skipped half to dominate:
#   S=1024: composed 23.9ms  pallas-tuned 24.2ms   (parity, within noise)
#   S=2048: composed 29.6ms  pallas-tuned 27.7ms   (pallas)
#   S=4096: composed 30.7ms  pallas-tuned 20.1ms   (pallas, 1.5x)
#   (default blocks for reference: 10.0/23.9/78.3ms at 1024/2048/4096)
# Selection: the tuned pallas kernel for causal attention from S>=2048
# (the isolated A/B is parity at 1024, but inside the full compiled
# flagship step composed still edges it there — 64.2% vs 62.6% MFU
# measured — so the threshold sits where the win is real), and for ANY
# shape whose fp32 score matrix exceeds the memory threshold (composed
# materializes O(B*H*S^2) scores; flash is O(S)). Non-causal below the
# threshold stays composed — there is no triangle to skip and XLA's
# fused attention is at parity or better there.
_COMPOSED_SCORE_BYTES_MAX = 2 << 30
_PALLAS_CAUSAL_MIN_SEQ = 2048


def _tuned_block_sizes(sq, sk):
    """v5e-tuned BlockSizes (measured above); clamped for short seqs."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
    )

    bq = min(512, sq)
    bkm = min(1024, sk)
    bk = min(512, sk)
    return BlockSizes(
        block_q=bq, block_k_major=bkm, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkm, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bkm, block_k_dq=bk, block_q_dq=bq,
    )


def _pallas_ok(q, k, v, causal):
    if all(d.platform == "cpu" for d in jax.devices()):
        return False
    if _pallas_fa() is None:
        return False
    b, sq, h, d = q.shape
    sk = k.shape[1]
    score_bytes = 4 * b * h * sq * sk  # fp32 softmax intermediate
    wanted = (
        # sq == sk required: for cross-length causal attention the
        # pallas kernel's top-left-aligned causal mask disagrees with
        # composed's bottom-right-aligned one (tril k=sk-sq)
        (causal and sq == sk and sk >= _PALLAS_CAUSAL_MIN_SEQ)
        or (not causal and score_bytes > _COMPOSED_SCORE_BYTES_MAX)
        or (causal and sq == sk
            and score_bytes > _COMPOSED_SCORE_BYTES_MAX)
    )
    if not wanted:
        return False
    # the kernel asserts divisibility by its ACTUAL block sizes (the
    # tuned ones we pass, not the 128-lane minimum) on both q and kv
    # sides; anything else falls back to composed
    bs = _tuned_block_sizes(sq, sk)
    return (
        sq % bs.block_q == 0
        and sq % bs.block_q_dq == 0
        and sq % bs.block_q_major_dkv == 0
        and sk % bs.block_k_major == 0
        and sk % bs.block_k == 0
        and v.shape[1] == sk
        and d in (64, 128, 256)
    )


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """q/k/v: [B, S, H, D] -> [B, S, H, D].

    q and k/v may arrive in different dtypes (bf16 KV caches from the
    serving pool / ``generate(cache_dtype=...)``, or fp32 caches under
    a bf16-activation model): align everything to the PROMOTED dtype —
    always widening, never rounding a wider cache down — so the Pallas
    kernel sees uniform operands and the composed path gets exactly the
    promotion XLA would insert."""
    ct = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype), v.dtype)
    if q.dtype != ct:
        q = q.astype(ct)
    if k.dtype != ct:
        k = k.astype(ct)
    if v.dtype != ct:
        v = v.astype(ct)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_ok(q, k, v, causal):
        fa = _pallas_fa()
        # pallas kernel layout: [B, H, S, D]
        out = fa(
            jnp.swapaxes(q, 1, 2),
            jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2),
            causal=causal,
            sm_scale=scale,
            block_sizes=_tuned_block_sizes(q.shape[1], k.shape[1]),
        )
        return jnp.swapaxes(out, 1, 2)
    return _composed(q, k, v, causal=causal, scale=scale)
