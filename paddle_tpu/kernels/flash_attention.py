"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (paddle/phi/kernels/gpu/
flash_attn_kernel.cu wrapping the flash-attention lib — unverified, mount
empty). On TPU the equivalent is a Pallas blockwise-softmax kernel; jax
ships a production-quality one (jax.experimental.pallas.ops.tpu.flash_attention)
which we use when shapes allow, with a composed-jnp fallback otherwise.
Layout contract matches paddle: q/k/v are [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _composed(q, k, v, *, causal, scale):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=1)
def _pallas_fa():
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        return flash_attention
    except Exception:
        return None


# Measured on TPU v5e (fwd+bwd, causal, H=16 D=64, 8192 tokens total):
#   S=512:  composed 22.2ms  pallas 21.6ms
#   S=1024: composed 12.7ms  pallas 22.4ms
#   S=2048: composed 20.5ms  pallas 31.1ms
#   S=4096: composed 21.6ms  pallas 47.7ms
#   S=8192: composed 37.1ms  pallas 78.6ms
# XLA's fused attention beats the generic pallas flash kernel on time at
# every size tested, so the pallas path is selected on MEMORY grounds
# only: composed materializes O(B*H*S^2) scores (fp32 for the softmax),
# which stops fitting alongside a real model's activations somewhere in
# the multi-GB range. Above the threshold flash's O(S) memory wins.
_COMPOSED_SCORE_BYTES_MAX = 2 << 30


def _pallas_ok(q, k, v):
    if all(d.platform == "cpu" for d in jax.devices()):
        return False
    if _pallas_fa() is None:
        return False
    b, sq, h, d = q.shape
    score_bytes = 4 * b * h * sq * k.shape[1]  # fp32 softmax intermediate
    if score_bytes <= _COMPOSED_SCORE_BYTES_MAX:
        return False  # composed is faster whenever it fits (see table)
    # pallas kernel wants seq multiples of its block sizes on BOTH q and kv
    # sides and a supported head_dim; anything else falls back to composed
    return (
        sq % 128 == 0
        and k.shape[1] % 128 == 0
        and v.shape[1] == k.shape[1]
        and d in (64, 128, 256)
    )


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """q/k/v: [B, S, H, D] -> [B, S, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_ok(q, k, v):
        fa = _pallas_fa()
        # pallas kernel layout: [B, H, S, D]
        out = fa(
            jnp.swapaxes(q, 1, 2),
            jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2),
            causal=causal,
            sm_scale=scale,
        )
        return jnp.swapaxes(out, 1, 2)
    return _composed(q, k, v, causal=causal, scale=scale)
