"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (paddle/phi/kernels/gpu/
flash_attn_kernel.cu wrapping the flash-attention lib — unverified, mount
empty). On TPU the equivalent is a Pallas blockwise-softmax kernel; jax
ships a production-quality one (jax.experimental.pallas.ops.tpu.flash_attention)
which we use when shapes allow, with a composed-jnp fallback otherwise.
Layout contract matches paddle: q/k/v are [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _composed(q, k, v, *, causal, scale):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=1)
def _pallas_fa():
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        return flash_attention
    except Exception:
        return None


def _pallas_ok(q, k, v):
    if all(d.platform == "cpu" for d in jax.devices()):
        return False
    if _pallas_fa() is None:
        return False
    # pallas kernel wants seq multiples of its block sizes on BOTH q and kv
    # sides and a supported head_dim; anything else falls back to composed
    d = q.shape[-1]
    return (
        q.shape[1] % 128 == 0
        and k.shape[1] % 128 == 0
        and v.shape[1] == k.shape[1]
        and d in (64, 128, 256)
    )


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """q/k/v: [B, S, H, D] -> [B, S, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_ok(q, k, v):
        fa = _pallas_fa()
        # pallas kernel layout: [B, H, S, D]
        out = fa(
            jnp.swapaxes(q, 1, 2),
            jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2),
            causal=causal,
            sm_scale=scale,
        )
        return jnp.swapaxes(out, 1, 2)
    return _composed(q, k, v, causal=causal, scale=scale)
