"""Multi-tensor fused Adam.

Reference parity: phi FusedAdamKernel / multi-tensor adam
(paddle/phi/kernels/gpu/fused_adam_kernel.cu — unverified, mount empty).
TPU design note: the reference needs a hand-written multi-tensor CUDA
kernel to avoid per-tensor launch overhead; under XLA a single jitted
tree-mapped update IS the fused kernel — XLA fuses the whole parameter
sweep into a few loops and there are no per-op launches. This module
provides that single-dispatch update over arbitrary pytrees with donated
buffers (used by CompiledTrainStep and callable standalone).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(9,))
def fused_adam_update(params, m, v, grads, lr, beta1, beta2, eps, t,
                      decoupled=False, weight_decay=0.0):
    """One compiled update over the whole parameter pytree."""

    def upd(p, m_, v_, g):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        # weight_decay is traced (may change per bucket/step); wd=0 is an
        # arithmetic no-op so no branch is needed. `decoupled` is static.
        if not decoupled:
            g32 = g32 + weight_decay * p32
        m2 = beta1 * m_ + (1 - beta1) * g32
        v2 = beta2 * v_ + (1 - beta2) * jnp.square(g32)
        mhat = m2 / (1 - jnp.power(beta1, t))
        vhat = v2 / (1 - jnp.power(beta2, t))
        if decoupled:
            p32 = p32 * (1 - lr * weight_decay)
        return (p32 - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype), m2, v2

    flat_p, tree = jax.tree_util.tree_flatten(params)
    flat_m = jax.tree_util.tree_leaves(m)
    flat_v = jax.tree_util.tree_leaves(v)
    flat_g = jax.tree_util.tree_leaves(grads)
    outs = [upd(p, m_, v_, g) for p, m_, v_, g in zip(flat_p, flat_m, flat_v, flat_g)]
    new_p = jax.tree_util.tree_unflatten(tree, [o[0] for o in outs])
    new_m = jax.tree_util.tree_unflatten(tree, [o[1] for o in outs])
    new_v = jax.tree_util.tree_unflatten(tree, [o[2] for o in outs])
    return new_p, new_m, new_v
