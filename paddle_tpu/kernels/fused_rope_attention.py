"""Fused rotary-embedding + attention — Pallas TPU kernel.

The unfused train path runs THREE passes over q/k: the rope kernel
writes a rotated copy of q and of k back to HBM (kernels/rope.py), then
attention reads both again. This kernel applies the rotation inside the
attention kernel's q/k load — the rotated tensors never exist in HBM,
and the per-block score tile stays in VMEM (composed attention
materializes the full O(B*H*S^2) score tensor).

Shape contract: q/k/v are [B, S, H, D] (paddle layout), cos/sin are the
half-dim rope tables ([1, S, 1, D/2] as built by
``kernels.rope.build_rope_cache``, or plain [S, D/2]). Self-attention
only (q and k share one sequence length and one position table) — the
training/prefill shape. Per (batch, head, q-block) grid step the kernel
rotates its q rows with their table rows, rotates + scores the full k,
and softmaxes in fp32; block_q is the tuned knob
(``autotune.rope_attention_candidates``).

Backward runs through the composed reference (``custom_vjp`` whose bwd
is the VJP of :func:`rope_attention_composed` — mathematically the same
function), so fwd+bwd training steps can select the fused forward
without a hand-written backward kernel.

Selection is tune-cache OPT-IN (:func:`rope_attention_select`): with no
cache entry for the exact (shape, device) signature, call sites keep
today's unfused path byte-identical; ``bench.py --tune`` /
``tools/kernel_tune.py`` measure and record entries.

Falls back to pallas interpret mode off-TPU (CI) — same code path, host
execution.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from .autotune import interpret_mode as _interpret


def _table_2d(t):
    """Accept [1, S, 1, D/2] (build_rope_cache) or [S, D/2]; return
    [S, D/2] jnp array."""
    v = t.value if hasattr(t, "value") else jnp.asarray(t)
    if v.ndim == 4:
        v = v.reshape(v.shape[1], v.shape[3])
    if v.ndim != 2:
        raise ValueError(
            f"rope table must be [1,S,1,D/2] or [S,D/2], got {v.shape}"
        )
    return v


def _rotate(x, cos, sin):
    """Neox-style rotation, fp32 in fp32 out; cos/sin broadcast over
    leading dims. Must stay op-for-op identical between the kernel body
    and the composed reference (bit-exact parity is pinned in CI)."""
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2]
    x2 = x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1)


def _attn_rows(s, *, causal, row0, scale):
    """Score rows -> attention weights, fp32; shared op order with the
    composed reference. ``row0``: global index of the first query row
    (for the causal mask)."""
    s = s * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape,
                                               s.ndim - 2)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def _fused_kernel(q_ref, k_ref, v_ref, cos_ref, sin_ref, o_ref, *,
                  scale, causal, block_q):
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)      # [bq, D]
    k = k_ref[0, 0].astype(jnp.float32)      # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)      # [S, D]
    cos = cos_ref[:].astype(jnp.float32)     # [S, D/2]
    sin = sin_ref[:].astype(jnp.float32)
    row0 = i * block_q
    cos_q = jax.lax.dynamic_slice_in_dim(cos, row0, block_q, axis=0)
    sin_q = jax.lax.dynamic_slice_in_dim(sin, row0, block_q, axis=0)
    rq = _rotate(q, cos_q, sin_q)
    rk = _rotate(k, cos, sin)
    # contract d-with-d directly (no rk.T): the same dot_general
    # dimension numbers the composed reference's einsum lowers to, so
    # the two paths round identically (bit-exact parity pin)
    s = jax.lax.dot_general(rq, rk, (((1,), (1,)), ((), ())))
    p = _attn_rows(s, causal=causal, row0=row0, scale=scale)
    o_ref[0, 0] = jnp.dot(p, v).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _rope_attention(q, k, v, cos, sin, causal, scale, block_q):
    b, s, h, d = q.shape
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = pl.pallas_call(
        functools.partial(_fused_kernel, scale=scale, causal=causal,
                          block_q=block_q),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda i, j, t: (i, j, t, 0)),
            pl.BlockSpec((1, 1, s, d), lambda i, j, t: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda i, j, t: (i, j, 0, 0)),
            pl.BlockSpec((s, d // 2), lambda i, j, t: (0, 0)),
            pl.BlockSpec((s, d // 2), lambda i, j, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda i, j, t: (i, j, t, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=_interpret(),
    )(qt, kt, vt, cos, sin)
    return jnp.swapaxes(out, 1, 2)


def _composed_2d_tables(q, k, v, cos, sin, causal, scale):
    # [B, S, H, D] -> [B, H, S, D], all-fp32 through the attention (the
    # fused kernel keeps everything in VMEM fp32; op order must match)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    c = cos.astype(jnp.float32)[None, None]
    si = sin.astype(jnp.float32)[None, None]
    rq = _rotate(qt, c, si)
    rk = _rotate(kt, c, si)
    p = _attn_rows(jnp.einsum("bhqd,bhkd->bhqk", rq, rk), causal=causal,
                   row0=0, scale=scale)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)


def _fwd(q, k, v, cos, sin, causal, scale, block_q):
    return (
        _rope_attention(q, k, v, cos, sin, causal, scale, block_q),
        (q, k, v, cos, sin),
    )


def _bwd(causal, scale, block_q, res, g):
    q, k, v, cos, sin = res
    _, vjp = jax.vjp(
        lambda qv, kv, vv: _composed_2d_tables(qv, kv, vv, cos, sin,
                                               causal, scale),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_rope_attention.defvjp(_fwd, _bwd)


def rope_attention_fused(q, k, v, cos, sin, causal=True, scale=None,
                         block_q=None):
    """Fused rope+attention. q/k/v: [B, S, H, D]; cos/sin: rope tables
    ([1, S, 1, D/2] or [S, D/2]). Self-attention shapes only."""
    b, s, h, d = (int(x) for x in q.shape)
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"fused rope+attention is self-attention only: q {q.shape} "
            f"k {k.shape} v {v.shape}"
        )
    cos2 = _table_2d(cos)
    sin2 = _table_2d(sin)
    if cos2.shape != (s, d // 2) or sin2.shape != (s, d // 2):
        raise ValueError(
            f"rope tables must cover [S={s}, D/2={d // 2}], got "
            f"{cos2.shape}/{sin2.shape}"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if block_q is None:
        from . import autotune

        cands = autotune.rope_attention_candidates(s)
        if not cands:
            raise ValueError(f"S={s} has no legal block_q")
        block_q = cands[0]["block_q"]
    if s % int(block_q):
        raise ValueError(f"block_q={block_q} does not divide S={s}")
    return _rope_attention(q, k, v, cos2, sin2, bool(causal),
                           float(scale), int(block_q))


def rope_attention_composed(q, k, v, cos, sin, causal=True, scale=None):
    """Composed reference (plain jnp, XLA-fused): rotate q/k, then
    attention — op-for-op the math of the fused kernel, without the
    fusion. The parity tests pin the two bit-exact; the backward pass of
    :func:`rope_attention_fused` runs through this function's VJP."""
    d = int(q.shape[-1])
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    return _composed_2d_tables(q, k, v, _table_2d(cos), _table_2d(sin),
                               bool(causal), float(scale))


def rope_attention_select(b, s, h, d):
    """Tune-cache OPT-IN selection: the fused kernel's config when a
    measured entry exists for this exact shape on this device, else
    None (call sites keep the unfused path — byte-identical to the
    pre-autotuner behavior). A cached-but-illegal (stale) config is a
    counted, one-shot-warned fallback."""
    from . import autotune

    if d % 2 or s < 8:
        return None
    sig = autotune.rope_attention_sig(b, s, h, d)
    entry = autotune.lookup_entry("rope_attention", sig)
    if entry is None:
        return None
    cfg = dict(entry["config"])
    if not autotune.rope_attention_config_legal(s, cfg):
        autotune.note_fallback("rope_attention", sig, "stale-config",
                               detail=f"cached {cfg} illegal for S={s}")
        return None
    if entry.get("fused_beats_composed") is False:
        # the tuner measured composed FASTER for this exact shape on
        # this device — a measured policy decision, not a fallback
        autotune.note_selection("rope_attention", "composed:measured")
        return None
    autotune.note_selection("rope_attention", "fused:cached")
    return cfg


def _apply_fn(qv, kv, vv, cv, sv, *, causal, scale, block_q):
    return rope_attention_fused(qv, kv, vv, cv, sv, causal=causal,
                                scale=scale, block_q=block_q)


def rope_attention_apply(q, k, v, cos, sin, *, causal=True, scale=None,
                         block_q=None):
    """Tensor-level entry (grad-recording via core.dispatch) for model
    code."""
    from ..core import dispatch

    return dispatch.apply(
        "rope_attention", _apply_fn, (q, k, v, cos, sin),
        {"causal": bool(causal), "scale": scale, "block_q": block_q},
    )
