"""Measured-search block-config autotuner for the Pallas kernels.

BENCH_NOTES proved the principle by hand: re-tuning the flash-attention
block sizes (8x128 defaults -> bq512/bkm1024/bk512 on v5e) flipped
"pallas always loses" into a 1.5x win at S=4096. This module generalizes
that one-off into infrastructure, in the spirit of CUDA-L2's
measured-search-over-schedules (PAPERS.md):

- **Keys.** Results are stored per ``(kernel, shape-signature,
  device-kind)``. Shape signatures are canonical strings built by the
  per-kernel helpers below (``flash_sig`` / ``rope_attention_sig`` /
  ``norm_matmul_sig``); device kinds are normalized
  (``jax.devices()[0].device_kind`` lowercased, spaces -> dashes, known
  aliases folded: a v5e chip reports "TPU v5 lite").
- **Measurement.** :func:`measured_search` times every candidate with
  the interleaved-median methodology the BENCH_NOTES r5 flash ablation
  validated: candidates are timed round-robin window by window (A/B/A/B
  ...), so a transient host slowdown hits every candidate equally
  instead of poisoning whichever one it landed on; the per-candidate
  number is the median across windows. The clock and the device-sync
  hook are injectable, so tests drive the whole search with a fake
  timer and zero wall-time dependence.
- **Persistence.** A JSON results cache (``tools/kernel_tune_cache.json``
  by default — checked in for v5e like the lint baseline; override with
  ``PADDLE_TPU_TUNE_CACHE``) fronted by an in-process memo. A corrupt or
  unreadable cache file degrades to "no entries" (callers fall back to
  their seeded defaults) and is counted, never raised.
- **Observability.** Selection decisions (pallas-vs-composed, cache
  hit/miss, fallback reason) publish ``paddle_kernels_*`` counters
  through the observability registry; a capability fallback additionally
  emits ONE warning per (kernel, signature, reason) and a
  flight-recorder event, so a long-context shape silently losing its
  1.5x win (the pre-autotuner failure mode) is impossible.

Candidate generation is divisibility-aware: generators only emit
configs every block of which divides the sequence/row extent it tiles,
so a shape that fails the seeded default's modulo checks gets a LEGAL
config instead of a silent composed fallback.
"""
from __future__ import annotations

import json
import os
import threading
import warnings

# ------------------------------------------------------------------ keys

# device_kind strings seen in the wild, folded to one canonical name so
# a cache tuned on one v5e host is valid on every v5e host
_DEVICE_ALIASES = {
    "tpu-v5-lite": "tpu-v5e",
    "tpu-v5lite": "tpu-v5e",
    "tpu-v5litepod": "tpu-v5e",
}


def normalize_device_kind(kind):
    k = str(kind).strip().lower().replace(" ", "-").replace("_", "-")
    return _DEVICE_ALIASES.get(k, k)


def device_kind():
    """Canonical device kind of the default backend ("cpu" off-chip)."""
    import jax

    d = jax.devices()[0]
    if d.platform == "cpu":
        return "cpu"
    return normalize_device_kind(getattr(d, "device_kind", d.platform))


def interpret_mode():
    """Whether pallas kernels must run in interpret mode (no real
    accelerator backend). Single home for every kernel module's gate."""
    import jax

    return all(d.platform == "cpu" for d in jax.devices())


def flash_sig(b, sq, sk, h, d, causal):
    return f"b{b}_sq{sq}_sk{sk}_h{h}_d{d}_c{int(bool(causal))}"


def rope_attention_sig(b, s, h, d):
    return f"b{b}_s{s}_h{h}_d{d}"


def norm_matmul_sig(rows, hidden, n_out):
    return f"r{rows}_h{hidden}_n{n_out}"


def paged_attention_sig(b, pages, page_size, h, kvh, d, quant=False):
    """Paged decode attention: B decode rows, a [B, pages] page table
    over page_size-token pages, H query heads over kvh KV heads.
    ``quant=True`` tags the int8-arena flavor (its own tuning entry —
    int8 page loads + in-VMEM dequant have a different profile)."""
    base = f"b{b}_p{pages}_ps{page_size}_h{h}_kv{kvh}_d{d}"
    return base + ("_q8" if quant else "")


def int8_matmul_sig(rows, hidden, n_out):
    """Weight-only int8 matmul (decode projections / lm_head): rows x
    hidden activations against an int8 [hidden, n_out] weight with
    per-output-channel scales."""
    return f"r{rows}_h{hidden}_n{n_out}"


def fp8_matmul_sig(m, k, n):
    """fp8 train matmul (AMP O3): [m, k] x [k, n], e4m3 operands with
    per-tensor scaling, fp32 accumulate."""
    return f"m{m}_k{k}_n{n}"


def cache_key(kernel, sig, device=None):
    return f"{kernel}|{sig}|{device or device_kind()}"


# ------------------------------------------------------------- observability


def _registry():
    from ..observability import get_registry

    return get_registry()


def selection_counter():
    return _registry().counter(
        "paddle_kernels_selection_total",
        help="kernel path selections at trace time, by kernel and path",
    )


def fallback_counter():
    return _registry().counter(
        "paddle_kernels_fallback_total",
        help="capability fallbacks to the composed path (a wanted fused "
             "kernel could not run), by kernel and reason",
    )


def cache_counter():
    return _registry().counter(
        "paddle_kernels_tune_cache_total",
        help="tune-cache lookups and writes, by event "
             "(hit/miss/corrupt/write)",
    )


def tune_error_counter():
    return _registry().counter(
        "paddle_kernels_tune_candidate_errors_total",
        help="tune candidates skipped because build/warmup raised "
             "(Mosaic rejection, VMEM overflow), by kernel",
    )


def note_selection(kernel, path):
    """Count a selection decision (path: pallas/fused/composed)."""
    selection_counter().inc(kernel=kernel, path=path)


_WARNED = set()
_WARNED_LOCK = threading.Lock()


def note_fallback(kernel, sig, reason, detail=""):
    """A WANTED fused path could not run: metric + one-shot warning +
    flight-recorder event. Never raises (telemetry must not fail a
    step)."""
    fallback_counter().inc(kernel=kernel, reason=reason)
    key = (kernel, sig, reason)
    with _WARNED_LOCK:
        first = key not in _WARNED
        if first:
            _WARNED.add(key)
    if first:
        warnings.warn(
            f"paddle_tpu.kernels: {kernel} did not take the tuned "
            f"fused path for shape {sig} (reason: {reason}"
            + (f", {detail}" if detail else "")
            + "); run tools/kernel_tune.py to measure a config or see "
            "paddle_kernels_fallback_total for occurrence counts",
            RuntimeWarning, stacklevel=3,
        )
        try:
            from ..observability import get_flight_recorder

            get_flight_recorder().note(
                "kernel_fallback", kernel=kernel, sig=sig, reason=reason,
                detail=detail,
            )
        except Exception:
            pass


def reset_warned():
    """Test hook: re-arm the one-shot fallback warnings."""
    with _WARNED_LOCK:
        _WARNED.clear()


# ------------------------------------------------------------------- cache

ENV_CACHE = "PADDLE_TPU_TUNE_CACHE"
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_CACHE_PATH = os.path.join(_REPO, "tools", "kernel_tune_cache.json")
CACHE_VERSION = 1


def default_cache_path():
    return os.environ.get(ENV_CACHE) or DEFAULT_CACHE_PATH


class TuneCache:
    """Persistent JSON result cache with an in-process memo.

    File schema::

        {"version": 1,
         "entries": {"<kernel>|<sig>|<device>": {
             "config": {...block sizes...},
             "source": "seed-..."|"measured",
             "timings_ms": {...}            # optional, per candidate
         }}}

    A corrupt file (truncated write, hand-edit gone wrong) is treated as
    empty — callers fall back to their seeded defaults — and counted in
    ``paddle_kernels_tune_cache_total{event="corrupt"}``.
    """

    def __init__(self, path=None):
        self.path = path or default_cache_path()
        self._lock = threading.RLock()
        self._entries = None  # lazy: key -> entry dict
        self.corrupt = False

    # -- load/save ----------------------------------------------------
    def _load(self):
        if self._entries is not None:
            return self._entries
        entries = {}
        try:
            with open(self.path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError("cache root is not an object")
            raw = data.get("entries", {})
            if not isinstance(raw, dict):
                raise ValueError("cache 'entries' is not an object")
            for k, v in raw.items():
                if isinstance(v, dict) and isinstance(v.get("config"), dict):
                    entries[k] = v
        except FileNotFoundError:
            pass
        except Exception:
            # corrupt cache: degrade to seeded defaults, loudly countable
            self.corrupt = True
            cache_counter().inc(event="corrupt")
            entries = {}
        self._entries = entries
        return entries

    def save(self):
        with self._lock:
            entries = dict(self._load())
        payload = {
            "version": CACHE_VERSION,
            "note": "kernel block-size autotuner results "
                    "(tools/kernel_tune.py; paddle_tpu/kernels/autotune.py)."
                    " Keys are kernel|shape_sig|device_kind.",
            "entries": {k: entries[k] for k in sorted(entries)},
        }
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, self.path)
        cache_counter().inc(event="write")

    # -- lookup/record ------------------------------------------------
    def lookup(self, kernel, sig, device=None, count=True):
        """Config dict for (kernel, sig, device) or None. Counts
        hit/miss in the registry unless ``count=False``."""
        key = cache_key(kernel, sig, device)
        with self._lock:
            entry = self._load().get(key)
        if count:
            cache_counter().inc(event="hit" if entry else "miss",
                                kernel=kernel)
        return dict(entry["config"]) if entry else None

    def entry(self, kernel, sig, device=None):
        with self._lock:
            e = self._load().get(cache_key(kernel, sig, device))
        return dict(e) if e else None

    def record(self, kernel, sig, config, device=None, source="measured",
               timings_ms=None, extra=None, save=True):
        key = cache_key(kernel, sig, device)
        entry = {"config": dict(config), "source": source}
        if timings_ms:
            entry["timings_ms"] = timings_ms
        if extra:
            entry.update(extra)
        with self._lock:
            self._load()[key] = entry
            if save:
                self.save()
        return entry

    def keys(self):
        with self._lock:
            return sorted(self._load())


_CACHE = None
_CACHE_LOCK = threading.Lock()


def get_cache() -> TuneCache:
    """The process-wide cache for ``default_cache_path()``. Re-resolved
    when the path changes (tests flip ``PADDLE_TPU_TUNE_CACHE``)."""
    global _CACHE
    path = default_cache_path()
    with _CACHE_LOCK:
        if _CACHE is None or _CACHE.path != path:
            _CACHE = TuneCache(path)
        return _CACHE


def reset_cache():
    """Test hook: drop the in-process memo so the next lookup re-reads
    the cache file."""
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None


def lookup(kernel, sig, device=None):
    return get_cache().lookup(kernel, sig, device)


def lookup_entry(kernel, sig, device=None):
    """Full cache entry (config + metadata like the tuner's
    ``fused_beats_composed`` verdict) or None; counts hit/miss like
    :func:`lookup`."""
    entry = get_cache().entry(kernel, sig, device)
    cache_counter().inc(event="hit" if entry else "miss", kernel=kernel)
    return entry


# -------------------------------------------------------- candidate configs


def _divisors(n, options):
    return [b for b in options if b <= n and n % b == 0]


def flash_block_candidates(sq, sk):
    """Divisibility-aware (block_q, block_k_major, block_k) candidates
    for the stock Pallas flash kernel. Every candidate is LEGAL for
    (sq, sk): each block divides the extent it tiles and block_k divides
    block_k_major. Ordered largest-first (the measured v5e optimum sits
    at the large end; when used as an untuned fallback the first entry
    is taken). Empty when sq or sk has no MXU-friendly divisor."""
    qs = _divisors(sq, (1024, 512, 256, 128))
    kms = _divisors(sk, (1024, 512, 256, 128))
    out = []
    for bq in qs:
        for bkm in kms:
            for bk in (1024, 512, 256, 128):
                if bk <= bkm and bkm % bk == 0 and sk % bk == 0:
                    out.append({"block_q": bq, "block_k_major": bkm,
                                "block_k": bk})
    return out


def flash_config_legal(sq, sk, config):
    """The stock kernel asserts divisibility by its ACTUAL block sizes
    on both the q and kv sides (fwd and both backward passes use the
    same triple here)."""
    try:
        bq = int(config["block_q"])
        bkm = int(config["block_k_major"])
        bk = int(config["block_k"])
    except (KeyError, TypeError, ValueError):
        return False
    if min(bq, bkm, bk) < 1 or bk > bkm:
        return False
    return sq % bq == 0 and sk % bkm == 0 and sk % bk == 0 and bkm % bk == 0


def rope_attention_candidates(s, h=None, d=None):
    """block_q candidates for the fused rope+attention kernel (one
    q-row block per grid step; k/v ride whole). Smaller blocks bound the
    bq x S score tile's VMEM footprint; larger amortize the k/v loads."""
    return [{"block_q": b} for b in _divisors(s, (512, 256, 128, 64, 32,
                                                  16, 8))]


def rope_attention_config_legal(s, config):
    try:
        bq = int(config["block_q"])
    except (KeyError, TypeError, ValueError):
        return False
    return bq >= 1 and s % bq == 0


def norm_matmul_candidates(rows, n_out):
    """(block_rows, block_cols) candidates for the rms_norm+matmul
    epilogue kernel."""
    brs = _divisors(rows, (256, 128, 64, 32, 16, 8, 4, 2, 1))
    bcs = _divisors(n_out, (2048, 1024, 512, 256, 128))
    return [{"block_rows": br, "block_cols": bc}
            for br in brs for bc in bcs]


def norm_matmul_config_legal(rows, n_out, config):
    try:
        br = int(config["block_rows"])
        bc = int(config["block_cols"])
    except (KeyError, TypeError, ValueError):
        return False
    return (br >= 1 and bc >= 1 and rows % br == 0 and n_out % bc == 0)


def paged_attention_candidates(kv_heads):
    """``block_kvh`` candidates for the paged decode attention kernel:
    KV heads handled per grid step. Larger blocks amortize the per-page
    table-indexed loads across more heads; smaller blocks bound the
    per-step VMEM footprint (the gathered V scratch is
    ``[block_kvh * group, S_virtual, D]`` fp32). Only divisors of the
    model's KV-head count are legal."""
    return [{"block_kvh": b}
            for b in _divisors(kv_heads, (8, 4, 2, 1))]


def paged_attention_config_legal(kv_heads, config):
    try:
        bk = int(config["block_kvh"])
    except (KeyError, TypeError, ValueError):
        return False
    return bk >= 1 and kv_heads % bk == 0


def int8_matmul_candidates(rows, n_out):
    """(block_rows, block_cols) candidates for the weight-only int8
    matmul — same output-tiling space as the norm+matmul epilogue
    kernel (the contraction dim rides whole either way)."""
    return norm_matmul_candidates(rows, n_out)


def int8_matmul_config_legal(rows, n_out, config):
    return norm_matmul_config_legal(rows, n_out, config)


def fp8_matmul_candidates(m=None, k=None, n=None):
    """The fp8 train-matmul path has no block-size knob (XLA owns the
    tiling of a plain fp8 dot); the single candidate exists so the
    tuner can record the measured fp8-vs-bf16 verdict for the shape."""
    return [{"format": "e4m3"}]


CANDIDATE_GENERATORS = {
    "flash_attention": flash_block_candidates,
    "rope_attention": rope_attention_candidates,
    "rms_norm_matmul": norm_matmul_candidates,
    "paged_attention": paged_attention_candidates,
    "int8_matmul": int8_matmul_candidates,
    "fp8_matmul": fp8_matmul_candidates,
}


# ---------------------------------------------------------- measured search


def _default_sync(x):
    import jax

    jax.block_until_ready(x)


def measured_search(candidates, build, *, iters=3, windows=3, warmup=1,
                    clock=None, sync=None):
    """Interleaved-median search over ``candidates``.

    ``build(config) -> callable`` returns a zero-arg runnable for the
    candidate (compile happens in warmup, outside the timed windows).
    Within each window every candidate is timed once (``iters`` calls +
    device sync), in round-robin order; the reported per-candidate time
    is the median across windows — the BENCH_NOTES r5 methodology, which
    makes a transient host slowdown a shared outlier window instead of a
    bias against one candidate.

    ``clock`` (default ``time.perf_counter``) and ``sync`` (default
    ``jax.block_until_ready``) are injectable so tests run the full
    search deterministically with a fake timer.

    Returns ``(best_config, table)``: the table holds one row per
    candidate — ``{"config", "median_s", "window_s"}`` — sorted
    fastest-first; ``best_config`` is the fastest candidate's config
    (``None`` when ``candidates`` is empty).
    """
    import time as _time

    clock = clock or _time.perf_counter
    sync = sync or _default_sync
    runners = []
    for cand in candidates:
        try:
            fn = build(cand)
            for _ in range(max(warmup, 0)):
                sync(fn())  # compile + steady-state entry, untimed
        except Exception as e:
            # one candidate failing to compile/run (Mosaic rejection,
            # VMEM overflow on an aggressive tile) must not abort the
            # whole search — skip it, keep measuring the rest
            tune_error_counter().inc()
            warnings.warn(
                f"autotune: candidate {cand} failed to build/run and "
                f"was skipped ({type(e).__name__}: {e})",
                RuntimeWarning, stacklevel=2,
            )
            continue
        runners.append((cand, fn))
    times = [[] for _ in runners]
    for _ in range(windows):
        for slot, (_, fn) in enumerate(runners):
            t0 = clock()
            out = None
            for _ in range(iters):
                out = fn()
            sync(out)
            times[slot].append(clock() - t0)
    table = []
    for (cand, _), ts in zip(runners, times):
        med = sorted(ts)[len(ts) // 2]
        table.append({"config": dict(cand),
                      "median_s": med / max(iters, 1),
                      "window_s": [round(t, 6) for t in ts]})
    table.sort(key=lambda r: r["median_s"])
    if not table:
        return None, []
    return dict(table[0]["config"]), table


# The cache-or-measure driver lives in tools/kernel_tune.py
# (``tune_shape``): it owns the composed-baseline interleaving and the
# fused-vs-composed verdict (entries carry ``fused_beats_composed``;
# the selection paths refuse to activate a fused kernel the tuner
# measured as slower), and this module stays the mechanism layer
# (search + cache + metrics) with exactly one home for each piece.
