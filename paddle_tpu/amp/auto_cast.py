"""Automatic mixed precision.

Reference parity: python/paddle/amp/auto_cast.py (unverified, mount empty).
TPU-first: the preferred low precision is bfloat16 (MXU-native; no loss
scaling needed). The dispatch-level AMP hook rewrites float32 inputs of
white-listed ops (matmul/conv — the MXU ops) to the low dtype, leaving
numerically sensitive ops (softmax/norm/loss reductions) in float32 —
the same O1 insertion point as the reference's generated dygraph functions.
O2 additionally keeps master weights via ``decorate``.

O3 (``CompiledTrainStep(amp_level="O3")``) goes one level further: the
matmuls themselves run with fp8 operands (e4m3 forward / e5m2 backward,
per-tensor delayed scaling — see ``paddle_tpu.amp.fp8``) while this
module's O1 white/black lists keep handling every other op. The fp8
routing needs carried scaling state, so it lives in the compiled train
step rather than in this stateless dispatch hook.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core import dispatch
from ..core.dtypes import convert_dtype

# ops that run in low precision under O1 (the MXU FLOP carriers)
WHITE_LIST = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "flash_attention", "scaled_dot_product_attention",
}
# ops forced to float32 (numerically sensitive)
BLACK_LIST = {
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax", "softmax",
    "layer_norm", "batch_norm_train", "batch_norm_infer", "rms_norm",
    "logsumexp", "mean", "sum", "norm", "group_norm", "nll_loss",
    "binary_cross_entropy", "bce_with_logits", "mse_loss", "l1_loss",
    "kl_div", "exp", "log", "pow", "erf",
}

white_list = WHITE_LIST  # paddle exposes these names


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_STATE = _AmpState()


def _amp_hook(op_name, vals):
    if not _STATE.enabled:
        return vals
    name = op_name
    low = _STATE.dtype
    in_white = (
        name in WHITE_LIST or name in _STATE.custom_white
    ) and name not in _STATE.custom_black
    in_black = name in BLACK_LIST or name in _STATE.custom_black
    out = []
    for v in vals:
        if v is None or not hasattr(v, "dtype"):
            out.append(v)
            continue
        if in_white and v.dtype == jnp.float32:
            out.append(v.astype(low))
        elif in_black and v.dtype == low:
            out.append(v.astype(jnp.float32))
        else:
            out.append(v)
    return out


dispatch.set_amp_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (
        _STATE.enabled, _STATE.dtype, _STATE.level,
        _STATE.custom_white, _STATE.custom_black,
    )
    _STATE.enabled = bool(enable)
    _STATE.dtype = jnp.dtype(convert_dtype(dtype))
    _STATE.level = level
    _STATE.custom_white = set(custom_white_list or ())
    _STATE.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (
            _STATE.enabled, _STATE.dtype, _STATE.level,
            _STATE.custom_white, _STATE.custom_black,
        ) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the low dtype; optimizer math stays fp32
    (the update kernels upcast internally — master-weight semantics)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


def is_auto_cast_enabled():
    return _STATE.enabled


def get_amp_dtype():
    return _STATE.dtype
