"""paddle.amp.debugging parity (python/paddle/amp/debugging.py —
unverified): numeric-health tooling for mixed-precision training.

Builds on the framework's check_nan_inf sweep (core/dispatch.py): the
eager path scans per-op outputs; inside compiled steps a debug callback
fires. This module adds the user-facing knobs + per-op stats."""
from __future__ import annotations

import contextlib

import numpy as np

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ..utils.flags import get_flags, set_flags


class DebugMode:
    """Reference enum surface (CHECK_NAN_INF_AND_ABORT is the acted-on
    mode; the others are accepted for API parity)."""

    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


def enable_tensor_checker(checker_config=None):
    """Turn on the per-op NaN/Inf sweep (FLAGS_check_nan_inf)."""
    set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Raise on NaN/Inf in ``tensor`` (reference check_numerics)."""
    v = np.asarray(
        tensor.numpy() if isinstance(tensor, Tensor) else tensor
    )
    bad = ~np.isfinite(v)
    if bad.any():
        raise FloatingPointError(
            f"check_numerics: {int(bad.sum())}/{v.size} non-finite values "
            f"in {op_type or 'tensor'} {var_name or ''} "
            f"(nan={int(np.isnan(v).sum())}, inf={int(np.isinf(v).sum())})"
        )
    return True


class _OpStats:
    def __init__(self):
        self.calls = {}

    def hook(self, name, seconds):
        cnt, total = self.calls.get(name, (0, 0.0))
        self.calls[name] = (cnt + 1, total + seconds)


_COLLECTOR = [None]
_PREV_HOOK = [None]


def enable_operator_stats_collection():
    """Start counting per-op dispatches (reference: low-precision op
    stats during amp training). Chains with an active Profiler hook
    instead of clobbering it."""
    _COLLECTOR[0] = _OpStats()
    _PREV_HOOK[0] = dispatch._PROFILER_HOOK[0]
    prev = _PREV_HOOK[0]
    stats = _COLLECTOR[0]

    def chained(name, seconds):
        stats.hook(name, seconds)
        if prev is not None:
            prev(name, seconds)

    dispatch._PROFILER_HOOK[0] = chained


def disable_operator_stats_collection():
    """Stop collecting and print the per-op call table; restores any
    previously-installed (profiler) hook."""
    col = _COLLECTOR[0]
    dispatch._PROFILER_HOOK[0] = _PREV_HOOK[0]
    _PREV_HOOK[0] = None
    _COLLECTOR[0] = None
    if col is None:
        return {}
    print(f"{'op':<32}{'calls':>8}{'total_ms':>12}")
    for name, (cnt, total) in sorted(
        col.calls.items(), key=lambda kv: -kv[1][1]
    ):
        print(f"{name:<32}{cnt:>8}{total * 1e3:>12.2f}")
    return {k: c for k, (c, _) in col.calls.items()}


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError(
        "compare_accuracy consumes the reference's GPU tensor-dump "
        "format; on this build use check_numerics / operator stats"
    )
