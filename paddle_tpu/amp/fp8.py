"""AMP "O3": fp8 train-step matmuls with per-tensor delayed scaling.

One level past O2: the big train-step matmuls (every ``F.linear`` —
the q/k/v/o projections, the SwiGLU gemms, the lm_head; the MXU FLOP
carriers) run with **e4m3 operands** in the forward and **e5m2
gradients** in the backward, fp32 accumulation, while everything else
keeps the O1 bf16/fp32 split. Weights crossing the HBM bus at 1 byte
instead of 2 is the win the :class:`~..observability.StepMeter`
reports analytically (``paddle_training_amp_fp8_matmul_bytes_saved``).

Scaling (the standard fp8 recipe):

- **Forward (delayed)**: each matmul site keeps an amax HISTORY per
  operand (``[HISTORY_LEN]`` fp32). The quantization scale for step t
  is derived from the history of steps < t — so the scale is known
  BEFORE the tensor is produced and quantization adds zero sync. The
  history is plain jit-carried state: ``CompiledTrainStep`` threads it
  through the compiled step next to the optimizer state (in/out every
  step as device arrays — structure discovered once via
  ``jax.eval_shape``, no extra compile, no host round trip).
- **Backward (just-in-time)**: incoming gradients quantize to e5m2
  with a scale from their OWN amax, computed in-trace — gradients are
  the tensors whose dynamic range moves fastest, and the JIT scale
  costs nothing extra inside the fused backward.

Saturation: values are clamped into the format's representable range
before the cast (graceful degradation while a history warms up — the
first step quantizes with scale 1).

Call sites route here via :func:`active` — the context is armed only
inside a ``CompiledTrainStep(amp_level="O3")`` trace (or an explicit
:func:`fp8_autocast`), so eager code and other AMP levels never pay
for the check beyond one thread-local read.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0          # max finite float8_e4m3fn
E5M2_MAX = 57344.0        # max finite float8_e5m2
HISTORY_LEN = 16          # amax history window per tensor site
_EPS = 1e-12


class _Fp8State(threading.local):
    def __init__(self):
        self.ctx = None


_TL = _Fp8State()


class Fp8Context:
    """Per-trace bookkeeping: serves each matmul site its delayed
    scale (from the carried history) and collects the updated
    histories, keyed by deterministic call order — tracing is
    deterministic, so site k is the same matmul every step."""

    def __init__(self, state):
        self.state = state or {}
        self.new_state = {}
        self._n = 0
        self.weight_bytes_saved = 0  # analytic, host-side static

    def site(self):
        k = f"linear{self._n}"
        self._n += 1
        return k

    def history(self, site, operand):
        key = f"{site}/{operand}"
        h = self.state.get(key)
        if h is None:
            h = jnp.zeros((HISTORY_LEN,), jnp.float32)
        return key, h


def active():
    return _TL.ctx is not None


def current():
    return _TL.ctx


@contextlib.contextmanager
def fp8_autocast(state=None):
    """Arm fp8 matmul routing for the enclosed (traced) region.
    ``state``: the carried {site/operand: amax-history} pytree from the
    previous step (None on discovery). The context's ``new_state``
    holds the updated histories to carry forward."""
    prev = _TL.ctx
    ctx = Fp8Context(state)
    _TL.ctx = ctx
    try:
        yield ctx
    finally:
        _TL.ctx = prev


def _delayed_scale(history, fmax):
    """Scale from the amax HISTORY (delayed scaling): amax/fmax with a
    margin-free floor — an empty history (all zeros) yields scale 1."""
    amax = jnp.max(history)
    return jnp.where(amax > 0, jnp.maximum(amax, _EPS) / fmax, 1.0)


def _roll_in(history, amax):
    """Newest amax enters at slot 0; the window slides."""
    return jnp.roll(history, 1).at[0].set(amax.astype(jnp.float32))


def _quantize(x, scale, dtype, fmax):
    """Scale, saturate into the format's range, cast. The cast IS the
    rounding step (round-to-nearest-even into fp8)."""
    y = x.astype(jnp.float32) / scale
    return jnp.clip(y, -fmax, fmax).astype(dtype)  # tpu-lint: quant


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fp8_dot(x_dtype, w_dtype, x2d, w, sx, sw):
    """[M, K] @ [K, N] with e4m3 operands / fp32 accumulate; scales are
    applied outside the dot (the epilogue rescale). ``x_dtype`` /
    ``w_dtype`` are the primal dtype NAMES (static) so the backward can
    emit cotangents in the right width."""
    qx = _quantize(x2d, sx, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _quantize(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
    out = jax.lax.dot_general(
        qx, qw, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out * (sx * sw)


def _fp8_dot_fwd(x_dtype, w_dtype, x2d, w, sx, sw):
    qx = _quantize(x2d, sx, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _quantize(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
    out = jax.lax.dot_general(
        qx, qw, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sx * sw)
    # residuals are the fp8 tensors — the memory the backward holds
    # per matmul drops 2-4x vs bf16/fp32 residuals
    return out, (qx, qw, sx, sw)


def _fp8_dot_bwd(x_dtype, w_dtype, res, g):
    qx, qw, sx, sw = res
    # e5m2 gradient with just-in-time per-tensor scale
    ga = jnp.max(jnp.abs(g))
    sg = jnp.where(ga > 0, jnp.maximum(ga, _EPS) / E5M2_MAX, 1.0)
    qg = _quantize(g, sg, jnp.float8_e5m2, E5M2_MAX)
    dx = jax.lax.dot_general(
        qg, qw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sg * sw)
    dw = jax.lax.dot_general(
        qx, qg, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sx * sg)
    # cotangent dtypes must match the primals'; scales came from
    # stop-gradient'd history state -> zero cotangents
    return (dx.astype(x_dtype), dw.astype(w_dtype),
            jnp.zeros_like(sx), jnp.zeros_like(sw))


_fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def fp8_linear_value(x, w, b):
    """The O3 body of ``F.linear`` (raw jax values, called inside the
    traced step): e4m3 x/w with delayed scales, output back in the
    compute dtype, bias added outside the fp8 path."""
    ctx = _TL.ctx
    site = ctx.site()
    kx, hx = ctx.history(site, "x")
    kw, hw = ctx.history(site, "w")
    sx = jax.lax.stop_gradient(_delayed_scale(hx, E4M3_MAX))
    sw = jax.lax.stop_gradient(_delayed_scale(hw, E4M3_MAX))
    shape = x.shape
    k = shape[-1]
    x2d = x.reshape(-1, k)
    out = _fp8_dot(jnp.dtype(x.dtype).name, jnp.dtype(w.dtype).name,
                   x2d, w, sx, sw).astype(x.dtype)
    out = out.reshape(tuple(shape[:-1]) + (w.shape[-1],))
    # update the carried histories with THIS step's amaxes (used from
    # the next step on — that is what makes the scaling "delayed")
    ctx.new_state[kx] = _roll_in(
        hx, jax.lax.stop_gradient(jnp.max(jnp.abs(
            x2d.astype(jnp.float32))))
    )
    ctx.new_state[kw] = _roll_in(
        hw, jax.lax.stop_gradient(jnp.max(jnp.abs(
            w.astype(jnp.float32))))
    )
    # analytic HBM delta: this matmul's weight crosses the bus as fp8
    # (1 byte) instead of its stored width
    try:
        ctx.weight_bytes_saved += int(w.size) * max(
            jnp.dtype(w.dtype).itemsize - 1, 0
        )
    except Exception:
        pass
    if b is not None:
        out = out + b
    return out


def note_selection_once():
    """Publish the O3 routing decision into the kernels selection
    series (telemetry only — never fails a step)."""
    try:
        from ..kernels import autotune

        autotune.note_selection("fp8_matmul", "fp8:o3")
    except Exception:
        pass
