"""Loss scaling.

Reference parity: python/paddle/amp/grad_scaler.py (unverified, mount
empty). On TPU bf16 training needs no loss scaling (full fp32 exponent
range), so with the default bf16 dtype this is numerically a no-op that
keeps the API contract; the dynamic-scaling machinery is still fully
implemented for float16 parity runs.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _grads_finite(self, optimizer):
        for _, p in optimizer._all_params():
            if p.grad is None:
                continue
            if not bool(jnp.all(jnp.isfinite(p.grad.value))):
                return False
        return True

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return  # guard: double-unscale would divide grads by scale twice
        self._found_inf = not self._grads_finite(optimizer)
        inv = 1.0 / self._scale
        for _, p in optimizer._all_params():
            if p.grad is not None:
                p.grad = Tensor(p.grad.value * inv)
        self._unscaled = True

    def step(self, optimizer):
        """paddle parity: step() does NOT update the loss scale — call
        update() afterwards (or use minimize(), which does both)."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
