"""paddle.amp parity (python/paddle/amp/ — unverified)."""
from .auto_cast import amp_guard, auto_cast, decorate, white_list  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
from . import debugging  # noqa: F401
from . import fp8  # noqa: F401
