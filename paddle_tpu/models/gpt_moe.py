"""GPT-MoE decoder (BASELINE config #5: GPT-MoE under Fleet EP).

Reference parity: the GPT + MoE pairing of the reference's incubate MoE
stack (python/paddle/incubate/distributed/models/moe — unverified, mount
empty; the GPT trunk itself lives in the ecosystem repos). TPU-first
design: pre-LN GPT blocks (learned positions, GELU) where every
``moe_every``-th block swaps its dense FFN for a MoELayer — experts
stacked [E, ...] and sharded over the ep mesh axes, GShard top-2 gating
with capacity/drop, einsum dispatch lowering to the all-to-all under
SPMD. The summed gate aux losses are exposed for the training loss.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..incubate.distributed.models.moe import MoELayer


@dataclass
class GPTMoEConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    num_experts: int = 8
    moe_every: int = 2  # every 2nd block uses the MoE FFN
    gate: str = "gshard"
    capacity_factor: tuple = (1.25, 2.0)
    layer_norm_eps: float = 1e-5
    aux_loss_weight: float = 0.01

    @staticmethod
    def tiny(**kw):
        base = dict(
            vocab_size=128, hidden_size=32, num_hidden_layers=4,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64, num_experts=4,
        )
        base.update(kw)
        return GPTMoEConfig(**base)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTMoEConfig):
        super().__init__()
        h = cfg.hidden_size
        self.heads = cfg.num_attention_heads
        self.head_dim = h // self.heads
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)

    def forward(self, x):
        b, s = int(x.shape[0]), int(x.shape[1])
        qkv = self.qkv(x).reshape([b, s, 3, self.heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, training=self.training
        )
        return self.proj(out.reshape([b, s, -1]))


class GPTMoEBlock(nn.Layer):
    def __init__(self, cfg: GPTMoEConfig, use_moe: bool):
        super().__init__()
        h = cfg.hidden_size
        self.ln1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.use_moe = use_moe
        if use_moe:
            self.mlp = MoELayer(
                d_model=h, num_expert=cfg.num_experts,
                d_hidden=cfg.intermediate_size,
                gate={"type": cfg.gate,
                      "capacity_factor": cfg.capacity_factor},
            )
        else:
            self.mlp = nn.Sequential(
                nn.Linear(h, cfg.intermediate_size),
                nn.GELU(),
                nn.Linear(cfg.intermediate_size, h),
            )

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self.mlp(self.ln2(x))


class GPTMoEForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTMoEConfig):
        super().__init__()
        if cfg.moe_every < 1:
            raise ValueError(
                f"moe_every must be >= 1, got {cfg.moe_every} (use the "
                "plain GPT/Llama families for an all-dense model)"
            )
        if cfg.num_hidden_layers < cfg.moe_every:
            raise ValueError(
                f"num_hidden_layers {cfg.num_hidden_layers} < moe_every "
                f"{cfg.moe_every}: no block would be MoE — this is the "
                "MoE model family"
            )
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size
        )
        self.blocks = nn.LayerList([
            GPTMoEBlock(cfg, use_moe=(i % cfg.moe_every == cfg.moe_every - 1))
            for i in range(cfg.num_hidden_layers)
        ])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_eps)
        self.lm_head = nn.Linear(
            cfg.hidden_size, cfg.vocab_size, bias_attr=False
        )

    def forward(self, input_ids):
        s = int(input_ids.shape[1])
        if s > int(self.wpe.weight.shape[0]):
            raise ValueError(
                f"sequence length {s} exceeds max_position_embeddings "
                f"{int(self.wpe.weight.shape[0])}"
            )
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        h = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            h = blk(h)
        return self.lm_head(self.ln_f(h))

    def aux_loss(self):
        """Summed gate load-balance losses of the MoE blocks (add
        ``cfg.aux_loss_weight * model.aux_loss()`` into the training
        loss inside the same step/trace)."""
        total = None
        for blk in self.blocks:
            if blk.use_moe and blk.mlp.l_aux is not None:
                total = blk.mlp.l_aux if total is None \
                    else total + blk.mlp.l_aux
        if total is None:
            raise RuntimeError(
                "aux_loss() before any forward: gate losses are recorded "
                "per step"
            )
        return total

    def num_params(self):
        return sum(int(p.size) for p in self.parameters())
