"""Flagship LLM model families (TPU-first).

The reference keeps its LLM zoo in the PaddleNLP ecosystem on top of the
core framework; this package ships the framework-native equivalents used
by the acceptance configs (BASELINE.json #3-#5): a Llama-family decoder
(RMSNorm/rope/flash-attention/SwiGLU) and a BERT encoder family
(fused post-LN attention/FFN blocks, tied MLM decoder, pretraining
criterion), both built on the fused-op API, sized by config, single-chip
or hybrid-parallel via fleet.
"""
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertModel,
    BertPretrainingCriterion,
)
from .gpt_moe import GPTMoEConfig, GPTMoEForCausalLM  # noqa: F401
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    causal_lm_loss,
)
from .llama_pipe import (  # noqa: F401
    LlamaDecoderLayerTP,
    LlamaForCausalLMPipe,
)
