"""Flagship LLM model families (TPU-first).

The reference keeps its LLM zoo in the PaddleNLP ecosystem on top of the
core framework; this package ships the framework-native equivalents used
by the acceptance configs (BASELINE.json #3-#5): a Llama-family decoder
built on the fused-op API (RMSNorm/rope/flash-attention/SwiGLU), sized by
config, single-chip or hybrid-parallel via fleet.
"""
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
