"""BERT encoder family (BASELINE config #3: BERT-base pretraining with
fused kernels).

Reference parity: the Fleet BERT pretraining config (BASELINE.json #3;
the model itself lives in PaddleNLP's bert modeling on top of core ops —
unverified, mount empty). TPU-first design: encoder blocks are the
incubate fused layers (FusedMultiHeadAttention / FusedFeedForward,
post-LN) — one QKV gemm, flash/composed attention via
F.scaled_dot_product_attention, gemm+bias+activation epilogues — so the
whole step compiles onto the MXU as a few fused loops. The MLM decoder
ties the word-embedding matrix (standard BERT weight tying).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..incubate.nn.layer import FusedFeedForward, FusedMultiHeadAttention


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    initializer_range: float = 0.02

    @staticmethod
    def tiny(**kw):
        base = dict(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64, type_vocab_size=2,
        )
        base.update(kw)
        return BertConfig(**base)

    @staticmethod
    def bert_base(**kw):
        return BertConfig(**kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size
        )
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size
        )
        self.layer_norm = nn.LayerNorm(
            cfg.hidden_size, epsilon=cfg.layer_norm_eps
        )
        self._dropout = cfg.hidden_dropout_prob

    def forward(self, input_ids, token_type_ids=None):
        s = int(input_ids.shape[1])
        max_s = int(self.position_embeddings.weight.shape[0])
        if s > max_s:
            raise ValueError(
                f"sequence length {s} exceeds max_position_embeddings "
                f"{max_s}"
            )
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros(tuple(input_ids.shape), jnp.int32)
            )
        h = h + self.token_type_embeddings(token_type_ids)
        h = self.layer_norm(h)
        return F.dropout(h, p=self._dropout, training=self.training)


def _init_bert_weights(layer, std):
    """Reference BERT init: weights ~ N(0, initializer_range), biases 0
    (LayerNorm params keep their 1/0 defaults)."""
    import jax

    from ..core import random as random_mod
    from ..nn.layer.norm import LayerNorm

    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, LayerNorm):
            continue
        for p in sub.parameters(include_sublayers=False):
            if len(p.shape) < 2:
                continue  # biases / 1-d params keep their zero defaults
            key = random_mod.next_key()
            p.value = (
                jax.random.normal(key, tuple(p.shape), jnp.float32) * std
            )


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        if cfg.hidden_act not in ("gelu", "relu"):
            raise ValueError(
                f"hidden_act {cfg.hidden_act!r} not supported (gelu/relu)"
            )
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder_layers = nn.LayerList([
            nn.LayerList([
                FusedMultiHeadAttention(
                    cfg.hidden_size, cfg.num_attention_heads,
                    dropout_rate=cfg.hidden_dropout_prob,
                    attn_dropout_rate=cfg.attention_probs_dropout_prob,
                    normalize_before=False, epsilon=cfg.layer_norm_eps,
                ),
                FusedFeedForward(
                    cfg.hidden_size, cfg.intermediate_size,
                    dropout_rate=cfg.hidden_dropout_prob,
                    activation=cfg.hidden_act,
                    normalize_before=False, epsilon=cfg.layer_norm_eps,
                ),
            ])
            for _ in range(cfg.num_hidden_layers)
        ])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        _init_bert_weights(self, cfg.initializer_range)

    @staticmethod
    def _additive_mask(attention_mask):
        """[B, S] 0/1 padding mask -> additive [B, 1, 1, S] bias."""
        m = attention_mask.cast("float32")
        return (1.0 - m).unsqueeze(1).unsqueeze(2) * -1e9

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        mask = (
            self._additive_mask(attention_mask)
            if attention_mask is not None else None
        )
        h = self.embeddings(input_ids, token_type_ids)
        for attn, ffn in self.encoder_layers:
            h = attn(h, attn_mask=mask)
            h = ffn(h)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertPretrainingHeads(nn.Layer):
    """MLM transform + tied decoder, and the NSP classifier."""

    def __init__(self, cfg: BertConfig, embedding_weights):
        super().__init__()
        self._act = getattr(F, cfg.hidden_act)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_ln = nn.LayerNorm(
            cfg.hidden_size, epsilon=cfg.layer_norm_eps
        )
        self._decoder_weight = embedding_weights  # tied [V, H]
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0),
        )
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output,
                masked_positions=None):
        h = sequence_output
        if masked_positions is not None:
            # gather only the masked slots before the big vocab gemm
            b, s, d = (int(x) for x in h.shape)
            flat = h.reshape([b * s, d])
            idx = masked_positions.reshape([-1])
            h = flat[idx]
        h = self.transform_ln(self._act(self.transform(h)))
        logits = F.linear(h, self._decoder_weight.t()) + self.decoder_bias
        return logits, self.seq_relationship(pooled_output)


class BertForPretraining(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.bert = BertModel(cfg)
        self.cls = BertPretrainingHeads(
            cfg, self.bert.embeddings.word_embeddings.weight
        )
        _init_bert_weights(self.cls, cfg.initializer_range)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.cls(seq, pooled, masked_positions)


class BertPretrainingCriterion(nn.Layer):
    """Masked-LM CE (ignore_index=-1 for unmasked slots) + NSP CE."""

    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels):
        mlm = F.cross_entropy(
            prediction_scores.reshape([-1, self.vocab_size]),
            masked_lm_labels.reshape([-1]),
            ignore_index=-1,
        )
        nsp = F.cross_entropy(
            seq_relationship_score, next_sentence_labels.reshape([-1])
        )
        return mlm + nsp
